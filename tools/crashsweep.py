#!/usr/bin/env python
"""Kill-restart convergence sweep: SIGKILL the pipeline anywhere, prove resume heals.

The resume model (CSV anti-join, shard-file checkpoints, stream-index npz
— SURVEY §5.4) has always been an *assumption*: no test ever killed the
process mid-write and asserted the invariants still hold.  This driver
turns it into a tested contract:

1. fork a REAL child running one of three workloads — CDX **harvest**,
   constant-rate **scrape**, **stream-dedup** — against mock transports
   with deterministic synthetic data;
2. SIGKILL it at a seeded random instant after it signals work start
   (or, in chaos mode, let ``ASTPU_CHAOS_FS`` with ``exit=1`` hard-exit
   it at a seeded byte offset *inside* a write syscall);
3. assert the kill-point safety property: every shard/npz checkpoint on
   disk is byte-complete or absent — never torn;
4. restart the same child clean and assert convergence: **zero URLs/docs
   lost, zero duplicated**, outputs equal to a never-killed run's.

Usage:
    python tools/crashsweep.py --kills 21 --seed 0        # full sweep
    python tools/crashsweep.py --child harvest --dir D --seed 3   # (internal)

The sweep functions are importable — ``tests/test_crash_recovery.py``
runs them in-process per workload.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MARKER = "WORK_STARTED"

#: reduced shard alphabet for child harvests: 6² = 36 shards instead of the
#: production 39² (the sweep needs a work window of ~1 s, not ~1 h)
SWEEP_CHARS = list("abc123")

SCRAPE_URLS = 80
STREAM_DOCS = 40
PINDEX_DOCS = 64
PINDEX_BANDS = 8
GRAPH_DOCS = 48

FLEET_DOCS = 64
FLEET_BATCH = 8
FLEET_SHARDS = 2
FLEET_REPLICAS = 2
#: seeded kill mechanisms for the fleet sweep, cycled per case: SIGKILL a
#: shard primary right before an insert-heavy batch / before a probe /
#: with the replica too (forcing spill + promotion-window recovery), or
#: chaos-exit the primary INSIDE a WAL append syscall
FLEET_KILL_MODES = ("insert", "probe", "promotion", "wal")

#: tenant workload: mixed two-tenant traffic through the service-plane
#: gateway (every request carries a tenant id; answers come from the
#: tenant's own key space) over the same 2×2 fleet, with a shard primary
#: SIGKILLed mid-stream — a node death must never leak one tenant's
#: postings into another's answers, and each tenant's stream must still
#: byte-match its single-node oracle
TENANT_DOCS = 56
TENANT_BATCH = 8
TENANT_IDS = ("acme", "bolt")

#: reshard workload: a live 2→4 cutover under the planted-dup stream with
#: the ORCHESTRATING child SIGKILLed at a seeded instant — landing mid
#: migration stream, mid dual-write window, or mid flip — or chaos-exited
#: INSIDE a migration-WAL (``reshard-wal-*``) write.  One replica per
#: shard keeps the case at four server processes; the reduced vnode count
#: keeps the plan at ~a dozen arcs so a kill window spans whole cutover
#: lifecycles instead of the first percent of one.
RESHARD_DOCS = 64
RESHARD_BATCH = 8
RESHARD_SHARDS = 2        # ring before the cutover
RESHARD_SHARDS_NEW = 4    # ring after
RESHARD_VNODES = 8

#: overload workload: a mixed-priority storm at ≥10× the shards' declared
#: write-admission capacity, with a mid-storm REPLICA SIGKILL — the
#: acceptance is zero collapse, ZERO promotions (a dead replica is not a
#: write-target loss; an overloaded node is not dead at all), counted
#: rejects with retry-after honored, and admitted-work annotations
#: byte-equal to an unloaded single-node oracle.
OVERLOAD_DOCS = 84
OVERLOAD_BATCH = 12
OVERLOAD_INSERT_RATE = 3.0   # per-node admitted writes/s (burst = rate)
OVERLOAD_STORM_WORKERS = 4   # read/ping storm threads beside the ingest


# -- deterministic synthetic data -------------------------------------------

def synth_cdx_text(prefix: str) -> str:
    """A fake CDX dump for one prefix: space-delimited, (date_time, url) in
    columns 1-2, every url carrying ``.html`` so the normalisation chain
    keeps it.  One url is shared across ALL prefixes so the merge step's
    global dedup has real work."""
    rows = [
        f"com,yahoo)/news/x 2020010100000{i} "
        f"https://finance.yahoo.com/news/{prefix}-doc{i}.html text/html 200 H 123"
        for i in range(6)
    ]
    rows.append(
        "com,yahoo)/news/x 20200101000099 "
        "https://finance.yahoo.com/news/shared-everywhere.html text/html 200 H 9"
    )
    return "\n".join(rows)


def harvest_expected_urls() -> set[str]:
    out = set()
    for a in SWEEP_CHARS:
        for b in SWEEP_CHARS:
            for i in range(6):
                out.add(f"https://finance.yahoo.com/news/{a}{b}-doc{i}.html")
    out.add("https://finance.yahoo.com/news/shared-everywhere.html")
    return out


def synth_article_page(url: str) -> str:
    tag = url.rsplit("/", 1)[-1]
    return (
        "<html><body>"
        f'<div class="cover-title">Article {tag}</div>'
        '<div class="body-wrap"><div class="body">'
        f"<p>Deterministic body for {tag}, long enough to be an article.</p>"
        "</div></div></body></html>"
    )


def synth_docs(n: int, seed: int = 0) -> list[str]:
    rng = random.Random(seed)
    alpha = "abcdefghijklmnopqrstuvwxyz "
    docs = ["".join(rng.choice(alpha) for _ in range(300)) for _ in range(n)]
    for i in range(0, n - 3, 7):  # planted near-dup pairs
        docs[i + 3] = docs[i][:250] + "".join(rng.choice(alpha) for _ in range(50))
    return docs


def _touch_marker(case_dir: str) -> None:
    with open(os.path.join(case_dir, MARKER), "w") as f:
        f.write(str(os.getpid()))


# -- child workloads ---------------------------------------------------------

def child_harvest(case_dir: str, seed: int) -> int:
    from advanced_scrapper_tpu.config import HarvestConfig
    from advanced_scrapper_tpu.net.transport import MockTransport
    from advanced_scrapper_tpu.pipeline import harvest

    harvest.CHAR_LIST = SWEEP_CHARS
    cfg = HarvestConfig(
        shard_dir=os.path.join(case_dir, "shards"),
        output_csv=os.path.join(case_dir, "yfin_urls.csv"),
        num_workers=4,
    )

    def serve(url: str) -> str:
        import re

        m = re.search(r"news/(\w+)\*", url)
        assert m, url
        return f"<html><body><pre>{synth_cdx_text(m.group(1))}</pre></body></html>"

    transport = MockTransport(serve, latency=0.02)
    _touch_marker(case_dir)
    return harvest.run_harvest(cfg, transport=transport, use_tpu=False)


def child_scrape(case_dir: str, seed: int) -> int:
    from advanced_scrapper_tpu.config import ScraperConfig
    from advanced_scrapper_tpu.net.transport import MockTransport
    from advanced_scrapper_tpu.pipeline.scraper import run_scraper

    cfg = ScraperConfig(
        website="yfin",
        input_csv=os.path.join(case_dir, "urls.csv"),
        out_dir=case_dir,
        desired_request_rate=400.0,
        max_threads=4,
        result_timeout=15.0,
        rate_limit_wait=0.1,
    )
    _touch_marker(case_dir)
    return run_scraper(
        cfg,
        transport_factory=lambda: MockTransport(synth_article_page, latency=0.01),
        with_tpu_backend=False,
        show_stats=False,
    )


def child_stream(case_dir: str, seed: int) -> int:
    """Streaming dedup with an npz checkpoint per processed batch and an
    annotations CSV as the exactly-once resume artifact (annotation-first
    ordering: a stale checkpoint only weakens dedup, never loses rows)."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    from advanced_scrapper_tpu.config import DedupConfig
    from advanced_scrapper_tpu.extractors.tpu_batch import TpuBatchBackend
    from advanced_scrapper_tpu.storage.csvio import AppendCsv, read_url_column

    cfg = DedupConfig(batch_size=16, block_len=512)
    ann_path = os.path.join(case_dir, "stream_annotations.csv")
    ckpt = os.path.join(case_dir, "stream_index.npz")
    docs = synth_docs(STREAM_DOCS, seed=seed)

    # repair=True: the annotations CSV is framework-owned, and this read
    # happens BEFORE AppendCsv reopens it — a torn key parsed leniently
    # here would be skipped as "done" and its row lost forever
    done = set(read_url_column(ann_path, column="url", repair=True))
    ann = AppendCsv(ann_path, ["url", "dup_of", "near_dup_of"])
    backend = TpuBatchBackend(
        cfg,
        sink=lambda rec: ann.write_row(
            {
                "url": rec.get("url", ""),
                "dup_of": rec.get("dup_of") or "",
                "near_dup_of": rec.get("near_dup_of") or "",
            }
        ),
        exact_stage=False,  # line-number keys are unique by construction
    )
    backend.load_index_if_valid(ckpt)
    # warm the jit cache on the real batch shape BEFORE the marker so the
    # sweep's kill window covers persistence work, not XLA compiles
    backend.engine.signatures(["w" * 300] * cfg.batch_size)
    _touch_marker(case_dir)
    try:
        for i, doc in enumerate(docs):
            key = f"L{i}"
            if key in done:
                continue
            if backend.submit({"article": doc, "url": key}):
                backend.save_index(ckpt)
        backend.flush()
        backend.save_index(ckpt)
    finally:
        ann.close()
    return 0


def _graph_digest(doc: str) -> str:
    import hashlib

    return hashlib.sha1(doc.encode()).hexdigest()[:16]


def child_graph(case_dir: str, seed: int) -> int:
    """Stage-graph runtime ingest: ingest → transform (2 workers) →
    persist, all queues owned by the scheduler, with the annotations CSV
    (through the fsio seam) as the exactly-once resume artifact.

    Paced stages keep items queued AND in flight for most of the run, and
    the source exhausts well before the pipeline drains — so seeded kill
    instants land both mid-stage and mid-drain.  The flight recorder is
    armed at a case-local sidecar: any chaos-fs fault (``fsio._die``)
    must dump a whole-graph drain snapshot (stage in-flight items, edge
    depths) before the process dies — the verifier asserts the sidecar
    holds one.
    """
    # force-set (never setdefault): the verifier reads THIS path, and an
    # operator-exported ASTPU_FLIGHT_RECORDER would otherwise redirect the
    # dump and silently skip the snapshot assertions
    os.environ["ASTPU_FLIGHT_RECORDER"] = os.path.join(case_dir, "flight.jsonl")
    from advanced_scrapper_tpu.runtime import DONE, StageGraph
    from advanced_scrapper_tpu.storage.csvio import AppendCsv, read_url_column

    ann_path = os.path.join(case_dir, "graph_annotations.csv")
    docs = synth_docs(GRAPH_DOCS, seed=seed % 7)  # corpus is seed-stable
    # repair=True: framework-owned artifact read BEFORE AppendCsv reopens
    # it — a torn key parsed leniently would be skipped as "done" forever
    done = set(read_url_column(ann_path, column="url", repair=True))
    ann = AppendCsv(ann_path, ["url", "digest"])
    todo = [(f"G{i}", docs[i]) for i in range(GRAPH_DOCS) if f"G{i}" not in done]

    graph = StageGraph("crashsweep_graph")
    raw = graph.edge("raw", capacity=4)
    cooked = graph.edge("cooked", capacity=4)
    it = iter(todo)

    def ingest():
        time.sleep(0.004)  # pace the source so queues stay occupied
        try:
            return next(it)
        except StopIteration:
            return DONE

    def transform(item):
        key, doc = item
        time.sleep(0.008)  # transform slower than ingest ⇒ real drain tail
        return (key, _graph_digest(doc))

    def persist(item):
        key, digest = item
        ann.write_row({"url": key, "digest": digest})
        return None

    graph.stage("ingest", source=ingest, out_edge=raw)
    graph.stage(
        "transform", fn=transform, in_edge=raw, out_edge=cooked, workers=2,
        # span propagation across edges: each item's key tags its
        # transform span, so the fault dump ties "what was in flight"
        # to named records, not just tuple[2] shapes
        tag=lambda item: {"key": item[0]},
    )
    graph.stage("persist", fn=persist, in_edge=cooked)
    _touch_marker(case_dir)
    graph.start()
    try:
        graph.join(timeout=120)
    finally:
        ann.close()
    return 0


def _pindex_doc_keys(i: int):
    """Deterministic uint64 band keys for synthetic doc ``i``; every doc
    with ``i % 7 == 3`` shares its keys with doc ``i - 3`` (a planted
    near-dup the index must catch across any kill/restart boundary)."""
    import numpy as np

    src = i - 3 if (i % 7 == 3 and i >= 3) else i
    x = (np.arange(PINDEX_BANDS, dtype=np.uint64)
         + np.uint64(src * 1000 + 1)) * np.uint64(0x9E3779B97F4A7C15)
    x ^= x >> np.uint64(29)
    return x


def _pindex_done_key(i: int):
    from advanced_scrapper_tpu.utils.bloom import hash_key64

    return hash_key64(f"L{i}")


def child_pindex(case_dir: str, seed: int) -> int:
    """Persistent-index ingest: probe-before-insert with the url key as the
    done marker, ONE atomic WAL record per doc (done key + band keys share
    the batch), tight cut/compaction cadence so the kill window lands
    inside WAL appends, segment cuts and compaction manifest swaps."""
    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex

    idx = PersistentIndex(
        os.path.join(case_dir, "pindex"),
        cut_postings=4 * (PINDEX_BANDS + 1),   # a cut every ~4 docs
        compact_segments=4,
        compact_inline=True,  # deterministic: compaction is a kill target
    )
    _touch_marker(case_dir)
    for i in range(PINDEX_DOCS):
        done = np.array([_pindex_done_key(i)], np.uint64)
        if int(idx.probe_batch(done)[0]) >= 0:
            continue  # this doc fully landed before a kill
        keys = _pindex_doc_keys(i)
        cand = int(idx.probe_batch(keys[None, :])[0])
        doc = int(idx.allocate_doc_ids(1)[0])
        if cand >= 0:
            # near-dup: only the done marker is posted
            idx.insert_batch(done, np.array([doc], np.uint64))
        else:
            # kept: done marker + band postings in ONE WAL record — the
            # crash atomicity unit (all-or-nothing on replay)
            idx.insert_batch(
                np.concatenate([done, keys]),
                np.full((1 + PINDEX_BANDS,), doc, np.uint64),
            )
        time.sleep(0.01)  # widen the wall-clock kill window
    idx.checkpoint()
    idx.close()
    return 0


def _fleet_doc_keys(i: int):
    """Band keys for fleet doc ``i`` — same planted-near-dup scheme as the
    pindex workload (``i % 7 == 3`` shares keys with ``i - 3``), under a
    distinct salt so fleet and pindex cases never alias."""
    import numpy as np

    src = i - 3 if (i % 7 == 3 and i >= 3) else i
    x = (np.arange(PINDEX_BANDS, dtype=np.uint64)
         + np.uint64(src * 1000 + 7)) * np.uint64(0xD1B54A32D192ED03)
    x ^= x >> np.uint64(31)
    return x


_FLEET_ORACLE_CACHE: list = []


def fleet_oracle_annotations():
    """The never-killed single-node truth the fleet must byte-match:
    batches of FLEET_BATCH docs through ONE PersistentIndex in a temp dir
    (allocate → check_and_add), annotations as int64 per doc.  Memoized —
    a pure function of module constants, and the 20-case sweep verifies
    against it per case."""
    if _FLEET_ORACLE_CACHE:
        return _FLEET_ORACLE_CACHE[0]
    import shutil
    import tempfile

    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex

    base = tempfile.mkdtemp(prefix="fleet-oracle-")
    idx = PersistentIndex(
        os.path.join(base, "oracle"),
        cut_postings=6 * PINDEX_BANDS,
        compact_segments=4,
        compact_inline=True,
    )
    ann: list[int] = []
    try:
        for start in range(0, FLEET_DOCS, FLEET_BATCH):
            rows = range(start, min(start + FLEET_BATCH, FLEET_DOCS))
            keys = np.stack([_fleet_doc_keys(i) for i in rows])
            ids = idx.allocate_doc_ids(len(keys))
            ann += np.asarray(idx.check_and_add_batch(keys, ids)).tolist()
        keys_all, docs_all = idx.dump_postings()
        minmap: dict[int, int] = {}
        for k, d in zip(keys_all.tolist(), docs_all.tolist()):
            if k not in minmap or d < minmap[k]:
                minmap[k] = d
    finally:
        idx.close()
        shutil.rmtree(base, ignore_errors=True)
    _FLEET_ORACLE_CACHE.append((ann, minmap))
    return ann, minmap


def _tenant_doc_keys(tenant: str, i: int):
    """Band keys for tenant doc ``i`` — the planted-dup scheme under a
    per-tenant crc32 salt, so the two tenants' corpora are KEY-DISJOINT
    by construction: any cross-tenant hit the sweep observes is a
    provable leak, not a collision."""
    import zlib

    import numpy as np

    salt = zlib.crc32(tenant.encode()) & 0xFFFFFFFF
    src = i - 3 if (i % 7 == 3 and i >= 3) else i
    x = (np.arange(PINDEX_BANDS, dtype=np.uint64)
         + np.uint64(src * 1000 + salt * 2 + 11)) * np.uint64(0xD1B54A32D192ED03)
    x ^= x >> np.uint64(31)
    return x


_TENANT_ORACLE_CACHE: dict = {}


def tenant_oracle(tenant: str):
    """One tenant's never-killed single-node truth: the same fixed-doc-id
    batch stream through ONE PersistentIndex.  Fixed ids make every
    insert idempotent, so a stream retried across the mid-case shard kill
    converges on these exact annotations.  Returns ``(annotations,
    probe answers per doc)``; memoized per tenant."""
    if tenant in _TENANT_ORACLE_CACHE:
        return _TENANT_ORACLE_CACHE[tenant]
    import shutil
    import tempfile

    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex

    base = tempfile.mkdtemp(prefix=f"tenant-oracle-{tenant}-")
    idx = PersistentIndex(
        os.path.join(base, "oracle"),
        cut_postings=6 * PINDEX_BANDS,
        compact_segments=4,
        compact_inline=True,
    )
    ann: list[int] = []
    try:
        for start in range(0, TENANT_DOCS, TENANT_BATCH):
            rows = range(start, min(start + TENANT_BATCH, TENANT_DOCS))
            keys = np.stack([_tenant_doc_keys(tenant, i) for i in rows])
            ids = np.asarray(list(rows), np.uint64)
            ann += np.asarray(idx.check_and_add_batch(keys, ids)).tolist()
        probes = np.asarray(
            idx.probe_batch(
                np.stack(
                    [_tenant_doc_keys(tenant, i) for i in range(TENANT_DOCS)]
                )
            ),
            np.int64,
        ).tolist()
    finally:
        idx.close()
        shutil.rmtree(base, ignore_errors=True)
    _TENANT_ORACLE_CACHE[tenant] = (ann, probes)
    return ann, probes


def _reshard_doc_keys(i: int):
    """Band keys for reshard doc ``i`` — the planted-dup scheme under its
    own salt (never aliases fleet/overload/pindex cases)."""
    import numpy as np

    src = i - 3 if (i % 7 == 3 and i >= 3) else i
    x = (np.arange(PINDEX_BANDS, dtype=np.uint64)
         + np.uint64(src * 1000 + 13)) * np.uint64(0xD1B54A32D192ED03)
    x ^= x >> np.uint64(31)
    return x


_RESHARD_ORACLE_CACHE: list = []


def reshard_oracle():
    """The never-resharded single-node truth the elastic cutover must
    byte-match: the same fixed-doc-id posting stream through ONE
    PersistentIndex (the reshard child posts ``doc=i`` directly — fixed
    ids make every insert idempotent across crash/resume, so the killed
    run and its resume converge on the same postings).  Returns
    ``(probe answers per doc, min-doc posting map)``; memoized."""
    if _RESHARD_ORACLE_CACHE:
        return _RESHARD_ORACLE_CACHE[0]
    import shutil
    import tempfile

    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex

    base = tempfile.mkdtemp(prefix="reshard-oracle-")
    idx = PersistentIndex(
        os.path.join(base, "oracle"),
        cut_postings=6 * PINDEX_BANDS,
        compact_segments=4,
        compact_inline=True,
    )
    try:
        for i in range(RESHARD_DOCS):
            keys = _reshard_doc_keys(i)
            idx.insert_batch(keys, np.full(keys.shape, i, np.uint64))
        probes = np.asarray(
            idx.probe_batch(
                np.stack([_reshard_doc_keys(i) for i in range(RESHARD_DOCS)])
            ),
            np.int64,
        ).tolist()
        keys_all, docs_all = idx.dump_postings()
        minmap: dict[int, int] = {}
        for k, d in zip(keys_all.tolist(), docs_all.tolist()):
            if k not in minmap or d < minmap[k]:
                minmap[k] = d
    finally:
        idx.close()
        shutil.rmtree(base, ignore_errors=True)
    _RESHARD_ORACLE_CACHE.append((probes, minmap))
    return probes, minmap


def _fleet_pick_ports(n: int) -> list[int]:
    """Reserve ``n`` distinct free ports up front: a killed node must be
    respawnable at the SAME address, so the client's failover/rejoin path
    is exercised without re-wiring the topology."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _fleet_spawn_server(
    case_dir: str, sid: int, rep: int, chaos: str | None, port: int,
    *, extra_args=(), telemetry: bool = False, metrics_port_file=None,
):
    """Fork one IndexShardServer over its (possibly crash-scarred) dir;
    PDEATHSIG ties it to the orchestrating child so a killed orchestrator
    can never leak a listening server into the next case."""
    import ctypes

    sdir = os.path.join(case_dir, f"s{sid}n{rep}")
    pf = os.path.join(case_dir, f"s{sid}n{rep}.port")
    if os.path.exists(pf):
        os.unlink(pf)
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        ASTPU_TELEMETRY="1" if telemetry else "0",
    )
    env.pop("ASTPU_CHAOS_FS", None)
    if chaos:
        env["ASTPU_CHAOS_FS"] = chaos
    # crash-sidecar harvesting: a chaos-exit INSIDE the shard dumps its
    # flight recorder here (SIGKILL leaves no dump — the CLIENT's own
    # sidecar names those kills via its failover events); the collector
    # pulls every *.flight.jsonl from the case dir centrally afterwards
    env["ASTPU_FLIGHT_RECORDER"] = os.path.join(
        case_dir, f"s{sid}n{rep}.flight.jsonl"
    )

    def _pdeathsig():
        ctypes.CDLL(None).prctl(1, signal.SIGKILL)  # PR_SET_PDEATHSIG

    log = open(os.path.join(case_dir, f"s{sid}n{rep}.log"), "ab")
    argv = [
        sys.executable, "-m", "advanced_scrapper_tpu.index.remote",
        "--dir", sdir, "--port", str(port), "--port-file", pf,
        "--spaces", "bands",
        "--cut-postings", str(6 * PINDEX_BANDS),
        "--compact-segments", "4",
        "--name", f"s{sid}n{rep}",
    ]
    if metrics_port_file:
        argv += ["--metrics-port-file", metrics_port_file]
    argv += list(extra_args)
    proc = subprocess.Popen(
        argv,
        env=env, cwd=REPO, stdout=log, stderr=log, preexec_fn=_pdeathsig,
    )
    log.close()
    deadline = time.monotonic() + 30
    while not os.path.exists(pf):
        if proc.poll() is not None or time.monotonic() > deadline:
            raise RuntimeError(f"shard server s{sid}n{rep} never bound")
        time.sleep(0.01)
    return proc


def child_fleet(case_dir: str, seed: int) -> int:
    """Fleet ingest under seeded shard-primary kills.

    Spawns FLEET_SHARDS×FLEET_REPLICAS real shard-server processes, runs
    the planted-dup batch stream through ShardedIndexClient, and at a
    seeded batch SIGKILLs a seeded shard's primary (mode-dependent:
    before an insert-heavy batch, before a probe, together with its
    replica — forcing journaled spill until the replica restarts — or via
    chaos-exit INSIDE a WAL append).  The client must carry the stream to
    completion through failover/promotion/spill-replay; annotations are
    written for the verifier to byte-compare against the single-node
    oracle, alongside the client's fault counters."""
    os.environ["ASTPU_TELEMETRY"] = "1"  # counters must be real in here
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
    from advanced_scrapper_tpu.obs import trace
    from advanced_scrapper_tpu.obs.slo import SloEngine

    # the client's own sidecar: its failover/spill/replay events name the
    # SIGKILLed shard (a SIGKILLed server can't dump; the survivor can)
    trace.set_dump_path(os.path.join(case_dir, "client.flight.jsonl"))

    rng = random.Random(f"fleet-child|{seed}")
    mode = FLEET_KILL_MODES[seed % len(FLEET_KILL_MODES)]
    kill_shard = rng.randrange(FLEET_SHARDS)
    n_batches = (FLEET_DOCS + FLEET_BATCH - 1) // FLEET_BATCH
    kill_batch = rng.randrange(2, n_batches - 2)
    revive_batch = min(n_batches - 1, kill_batch + 2)

    port_list = _fleet_pick_ports(FLEET_SHARDS * FLEET_REPLICAS)
    ports = {
        (sid, rep): port_list[sid * FLEET_REPLICAS + rep]
        for sid in range(FLEET_SHARDS)
        for rep in range(FLEET_REPLICAS)
    }
    procs: dict[tuple[int, int], subprocess.Popen] = {}
    try:
        for sid in range(FLEET_SHARDS):
            for rep in range(FLEET_REPLICAS):
                chaos = None
                if mode == "wal" and sid == kill_shard and rep == 0:
                    # hard-exit INSIDE a WAL append write, seeded offset
                    chaos = (
                        f"seed={seed},crash=0.35,exit=1,only=wal-"
                    )
                procs[(sid, rep)] = _fleet_spawn_server(
                    case_dir, sid, rep, chaos, ports[(sid, rep)]
                )
        spec = FleetSpec(
            shards=tuple(
                tuple(
                    ("127.0.0.1", ports[(sid, rep)])
                    for rep in range(FLEET_REPLICAS)
                )
                for sid in range(FLEET_SHARDS)
            )
        )
        client = ShardedIndexClient(
            spec,
            space="bands",
            spill_dir=os.path.join(case_dir, "spill"),
            timeout=1.0,
            retries=1,
            health_checks=2,
            health_timeout=0.3,
        )
        _touch_marker(case_dir)
        # declared fleet SLO: every shard must keep a proven write target;
        # evaluated after EVERY batch so the report records the exact
        # batch the objective flipped (one "scrape interval" = one batch)
        slo = SloEngine(
            [
                {
                    "name": "shards_healthy",
                    "kind": "gauge_min",
                    "metric": "astpu_fleet_shards_healthy",
                    "threshold": FLEET_SHARDS,
                    "agg": "min",
                }
            ]
        )
        slo_flipped_batch = None
        ann: list[int] = []
        for b in range(n_batches):
            if b == kill_batch and mode in ("insert", "probe", "promotion"):
                os.kill(procs[(kill_shard, 0)].pid, signal.SIGKILL)
                procs[(kill_shard, 0)].wait()
                if mode == "promotion":
                    # the candidate dies too, INSIDE the promotion the
                    # client is about to attempt: the shard goes fully
                    # dark and this window's writes must spill
                    os.kill(procs[(kill_shard, 1)].pid, signal.SIGKILL)
                    procs[(kill_shard, 1)].wait()
                if mode == "probe":
                    # land the discovery inside a probe, not an insert
                    client.probe_batch(
                        np.stack([_fleet_doc_keys(0), _fleet_doc_keys(1)])
                    )
                # the SLO "scrape interval" right after the kill: a probe
                # wide enough to touch every ring slice makes the client
                # OBSERVE the dead node (reads fail over instantly; the
                # shard stays in promotion until the next write proves a
                # target), and the declared shards_healthy floor must
                # flip HERE — before the healing write lands
                client.probe_batch(
                    np.stack([_fleet_doc_keys(i) for i in range(8)])
                )
                verdict = slo.evaluate()
                if slo_flipped_batch is None and not verdict["ok"]:
                    slo_flipped_batch = b
            if b == revive_batch and mode == "promotion":
                # the restarted node recovers its index from disk at the
                # SAME address; the client's next touches revive it,
                # promote it, and replay the spill journal into it
                procs[(kill_shard, 1)] = _fleet_spawn_server(
                    case_dir, kill_shard, 1, None, ports[(kill_shard, 1)]
                )
            rows = range(
                b * FLEET_BATCH, min((b + 1) * FLEET_BATCH, FLEET_DOCS)
            )
            keys = np.stack([_fleet_doc_keys(i) for i in rows])
            ids = client.allocate_doc_ids(len(keys))
            ann += np.asarray(client.check_and_add_batch(keys, ids)).tolist()
            verdict = slo.evaluate()
            if slo_flipped_batch is None and not verdict["ok"]:
                slo_flipped_batch = b
        client.checkpoint()  # recovery probe: drains any remaining spill
        final_verdict = slo.evaluate()
        # dump the client's ring and harvest EVERY sidecar centrally —
        # the collector must be able to name the dead shard from dumps
        # alone (the chaos-integration contract verify_fleet asserts)
        trace.dump(reason="fleet sweep end")
        from advanced_scrapper_tpu.obs.collector import FleetCollector

        harvester = FleetCollector(sidecar_dir=case_dir)
        harvester.harvest_sidecars()
        primary_died = procs[(kill_shard, 0)].poll() is not None
        report = {
            "mode": mode,
            "kill_shard": kill_shard,
            "kill_batch": kill_batch,
            "annotations": ann,
            "failovers": client._m_failovers.value,
            "promotions": client._m_promotions.value,
            "spilled": client._m_spilled.value,
            "replayed": client._m_replayed.value,
            "degraded": client._m_degraded.value,
            "spill_pending": sum(
                int(k.size) for sh in client._shards for (_r, k, _d) in sh.pending
            ),
            "slo_flipped_batch": slo_flipped_batch,
            "slo_final_ok": final_verdict["ok"],
            "slo_burn_fast": final_verdict["objectives"][0]["burn_fast"],
            "dead_shards": harvester.dead_shards(),
            "primary_died": primary_died,
        }
        client.close()
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            os.path.join(case_dir, "fleet_report.json"),
            json.dumps(report).encode(),
        )
        return 0
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def child_tenant(case_dir: str, seed: int) -> int:
    """Mixed two-tenant traffic through the front-door gateway under a
    seeded shard-primary SIGKILL.

    Spawns the 2×2 fleet, an in-process :class:`DedupGateway` over it,
    and drives both tenants' planted-dup streams batch-interleaved over
    loopback RPC — every request carrying its tenant id, every doc id
    FIXED (idempotent across the kill's failover window).  At a seeded
    batch the seeded shard's primary is SIGKILLed mid-mixed-traffic; the
    per-tenant fleet siblings must carry both streams to completion
    through failover/promotion.  The report holds each tenant's
    annotations + final probe matrix (byte-compared against
    :func:`tenant_oracle`) and a cross-tenant isolation sweep: tenant
    A's keys probed under B must all answer −1."""
    os.environ["ASTPU_TELEMETRY"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
    from advanced_scrapper_tpu.net.rpc import RpcClient
    from advanced_scrapper_tpu.service import (
        DedupGateway,
        TenantRegistry,
        TenantSpec,
    )

    rng = random.Random(f"tenant-child|{seed}")
    kill_shard = rng.randrange(FLEET_SHARDS)
    n_batches = (TENANT_DOCS + TENANT_BATCH - 1) // TENANT_BATCH
    kill_batch = rng.randrange(2, n_batches - 2)

    port_list = _fleet_pick_ports(FLEET_SHARDS * FLEET_REPLICAS)
    ports = {
        (sid, rep): port_list[sid * FLEET_REPLICAS + rep]
        for sid in range(FLEET_SHARDS)
        for rep in range(FLEET_REPLICAS)
    }
    procs: dict[tuple[int, int], subprocess.Popen] = {}
    gw = rc = client = None
    try:
        for sid in range(FLEET_SHARDS):
            for rep in range(FLEET_REPLICAS):
                procs[(sid, rep)] = _fleet_spawn_server(
                    case_dir, sid, rep, None, ports[(sid, rep)]
                )
        client = ShardedIndexClient(
            FleetSpec(
                shards=tuple(
                    tuple(
                        ("127.0.0.1", ports[(sid, rep)])
                        for rep in range(FLEET_REPLICAS)
                    )
                    for sid in range(FLEET_SHARDS)
                )
            ),
            space="bands",
            timeout=1.0,
            retries=2,
            health_checks=2,
            health_timeout=0.3,
        )
        gw = DedupGateway(
            client,
            registry=TenantRegistry(
                specs=[TenantSpec(tenant=t) for t in TENANT_IDS],
                auto_provision=False,
            ),
            name="sweep",
            spill_dir=os.path.join(case_dir, "spill"),
            stats_interval=0.0,
        ).start()
        rc = RpcClient(("127.0.0.1", gw.port), timeout=5.0, retries=3)
        _touch_marker(case_dir)
        ann: dict[str, list[int]] = {t: [] for t in TENANT_IDS}
        for b in range(n_batches):
            if b == kill_batch:
                os.kill(procs[(kill_shard, 0)].pid, signal.SIGKILL)
                procs[(kill_shard, 0)].wait()
            rows = range(
                b * TENANT_BATCH, min((b + 1) * TENANT_BATCH, TENANT_DOCS)
            )
            for t in TENANT_IDS:
                keys = np.stack([_tenant_doc_keys(t, i) for i in rows])
                ids = np.asarray(list(rows), np.uint64)
                _resp, arrays = rc.call(
                    "submit_batch", {"tenant": t}, [keys, ids]
                )
                ann[t] += np.asarray(arrays[0], np.int64).tolist()
        probes: dict[str, list[int]] = {}
        leaks = 0
        for t in TENANT_IDS:
            all_keys = np.stack(
                [_tenant_doc_keys(t, i) for i in range(TENANT_DOCS)]
            )
            _resp, arrays = rc.call("probe_batch", {"tenant": t}, [all_keys])
            probes[t] = np.asarray(arrays[0], np.int64).tolist()
            # the isolation sweep: this tenant's keys under EVERY other
            # tenant must be invisible
            for other in TENANT_IDS:
                if other == t:
                    continue
                _resp, arrays = rc.call(
                    "probe_batch", {"tenant": other}, [all_keys]
                )
                leaks += int((np.asarray(arrays[0], np.int64) >= 0).sum())
        failovers = promotions = spill_pending = 0
        with gw._lock:
            tenants = dict(gw._tenants)
        for t in tenants.values():
            failovers += t.client._m_failovers.value
            promotions += t.client._m_promotions.value
            spill_pending += sum(
                int(k.size)
                for sh in t.client._shards
                for (_r, k, _d) in sh.pending
            )
        report = {
            "kill_shard": kill_shard,
            "kill_batch": kill_batch,
            "annotations": ann,
            "probes": probes,
            "isolation_violations": leaks,
            "failovers": failovers,
            "promotions": promotions,
            "spill_pending": spill_pending,
        }
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            os.path.join(case_dir, "tenant_report.json"),
            json.dumps(report).encode(),
        )
        return 0
    finally:
        if rc is not None:
            rc.close()
        if gw is not None:
            gw.stop()
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def child_reshard(case_dir: str, seed: int) -> int:
    """Live elastic cutover under seeded orchestrator kills.

    Spawns RESHARD_SHARDS_NEW single-replica shard servers, streams the
    planted-dup corpus with FIXED doc ids through a client built on the
    2-shard ring, and at a seeded batch starts ``reshard_to`` the 4-shard
    ring on a background thread while the inserts keep flowing — so the
    parent's SIGKILL lands mid migration stream, mid dual-write window or
    mid flip (chaos mode instead hard-exits INSIDE a migration-WAL
    write).  The resumed child re-binds the SAME ports, reads the
    migration WAL to decide which ring reality is in (absent/active →
    old ring + resume the cutover; done → new ring), replays the
    idempotent stream, and reports the final probe matrix for the
    verifier to byte-compare against the single-node oracle."""
    os.environ["ASTPU_TELEMETRY"] = "1"  # counters must be real in here
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
    from advanced_scrapper_tpu.index.reshard import ReshardLedger, ledger_path
    from advanced_scrapper_tpu.obs import trace
    from advanced_scrapper_tpu.storage.fsio import atomic_replace

    trace.set_dump_path(os.path.join(case_dir, "client.flight.jsonl"))
    rng = random.Random(f"reshard-child|{seed}")
    n_batches = (RESHARD_DOCS + RESHARD_BATCH - 1) // RESHARD_BATCH
    reshard_batch = rng.randrange(1, n_batches - 2)

    # the topology must survive the orchestrator's own SIGKILL: the
    # resumed child re-binds the SAME ports so the specs sealed in the
    # migration WAL still name the running servers
    ports_path = os.path.join(case_dir, "ports.json")
    if os.path.exists(ports_path):
        with open(ports_path) as f:
            port_list = json.load(f)
    else:
        port_list = _fleet_pick_ports(RESHARD_SHARDS_NEW)
        atomic_replace(ports_path, json.dumps(port_list).encode())

    procs: dict[int, subprocess.Popen] = {}
    try:
        for sid in range(RESHARD_SHARDS_NEW):
            procs[sid] = _fleet_spawn_server(
                case_dir, sid, 0, None, port_list[sid]
            )

        def spec_of(n: int) -> FleetSpec:
            return FleetSpec(
                shards=tuple(
                    (("127.0.0.1", port_list[sid]),) for sid in range(n)
                )
            )

        new_spec = spec_of(RESHARD_SHARDS_NEW)
        spill = os.path.join(case_dir, "spill")
        led = ReshardLedger.load(ledger_path(spill, "bands"))
        done = led is not None and led.phase == "done"
        client = ShardedIndexClient(
            new_spec if done else spec_of(RESHARD_SHARDS),
            space="bands",
            spill_dir=spill,
            vnodes=RESHARD_VNODES,
            timeout=1.0,
            retries=1,
            health_checks=2,
            health_timeout=0.3,
        )
        _touch_marker(case_dir)
        stats_box: dict = {}

        def run_reshard() -> None:
            try:
                stats_box.update(client.reshard_to(new_spec))
            except BaseException as e:  # reported after the join
                stats_box["error"] = repr(e)

        t = None
        for b in range(n_batches):
            if b == reshard_batch and not done:
                t = threading.Thread(target=run_reshard, daemon=True)
                t.start()
            rows = range(
                b * RESHARD_BATCH, min((b + 1) * RESHARD_BATCH, RESHARD_DOCS)
            )
            # one doc per insert so the in-batch planted dup is filtered
            # by the server's semantic idempotency (probe-first), exactly
            # like a redelivery — the store never holds a key twice
            for i in rows:
                keys = _reshard_doc_keys(i)
                client.insert_batch(keys, np.full(keys.shape, i, np.uint64))
        if t is None and not done:
            # first run killed before the start batch: cut over now, so
            # every surviving case ends on the new ring
            t = threading.Thread(target=run_reshard, daemon=True)
            t.start()
        if t is not None:
            t.join(timeout=120)
            if t.is_alive():
                raise RuntimeError("reshard never finished inside 120 s")
            if "error" in stats_box:
                raise RuntimeError(f"reshard failed: {stats_box['error']}")
        client.checkpoint()  # recovery probe: drains any remaining spill
        probes = client.probe_batch(
            np.stack([_reshard_doc_keys(i) for i in range(RESHARD_DOCS)])
        )
        led2 = ReshardLedger.load(ledger_path(spill, "bands"))
        trace.dump(reason="reshard sweep end")
        report = {
            "resumed": led is not None,
            "reshard_batch": reshard_batch,
            "reshard": stats_box or None,
            "probes": np.asarray(probes, np.int64).tolist(),
            "ledger_phase": led2.phase if led2 else None,
            "all_retired": bool(led2.all_retired()) if led2 else False,
            "voids": int(led2.doc.get("voids", 0)) if led2 else 0,
            "route_shards": client._route_shards,
            "spill_pending": sum(
                int(k.size)
                for sh in client._shards
                for (_r, k, _d) in sh.pending
            ),
        }
        client.close()
        atomic_replace(
            os.path.join(case_dir, "reshard_report.json"),
            json.dumps(report).encode(),
        )
        return 0
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _overload_doc_keys(i: int):
    """Band keys for overload doc ``i`` — the planted-dup scheme under
    its own salt (never aliases fleet/pindex cases)."""
    import numpy as np

    src = i - 3 if (i % 7 == 3 and i >= 3) else i
    x = (np.arange(PINDEX_BANDS, dtype=np.uint64)
         + np.uint64(src * 1000 + 31)) * np.uint64(0x9E3779B97F4A7C15)
    x ^= x >> np.uint64(29)
    return x


_OVERLOAD_ORACLE_CACHE: list = []


def overload_oracle_annotations():
    """The UNLOADED single-node truth the stormed fleet must byte-match
    for every admitted item (and every item IS eventually admitted — the
    client's retry-after honoring turns overload into backpressure, not
    loss).  Memoized like the fleet oracle."""
    if _OVERLOAD_ORACLE_CACHE:
        return _OVERLOAD_ORACLE_CACHE[0]
    import shutil
    import tempfile

    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex

    base = tempfile.mkdtemp(prefix="overload-oracle-")
    idx = PersistentIndex(
        os.path.join(base, "oracle"),
        cut_postings=6 * PINDEX_BANDS,
        compact_segments=4,
        compact_inline=True,
    )
    ann: list[int] = []
    try:
        for start in range(0, OVERLOAD_DOCS, OVERLOAD_BATCH):
            rows = range(start, min(start + OVERLOAD_BATCH, OVERLOAD_DOCS))
            keys = np.stack([_overload_doc_keys(i) for i in rows])
            ids = idx.allocate_doc_ids(len(keys))
            ann += np.asarray(idx.check_and_add_batch(keys, ids)).tolist()
        keys_all, docs_all = idx.dump_postings()
        minmap: dict[int, int] = {}
        for k, d in zip(keys_all.tolist(), docs_all.tolist()):
            if k not in minmap or d < minmap[k]:
                minmap[k] = d
    finally:
        idx.close()
        shutil.rmtree(base, ignore_errors=True)
    _OVERLOAD_ORACLE_CACHE.append((ann, minmap))
    return ann, minmap


def child_overload(case_dir: str, seed: int) -> int:
    """10× mixed-priority storm against an admission-tight 2×2 fleet,
    with a seeded mid-storm REPLICA SIGKILL (+respawn).

    The shard servers declare ~3 admitted writes/s each; the ingest
    stream plus a read/ping storm offer far more.  The contract under
    test: the fleet backs off in place on counted rejects (retry-after
    honored), NEVER promotes (the write targets stay seated — overload
    is not death, and a dead replica is not a write-target loss), no
    probe degrades, and the admitted annotations land byte-equal to the
    unloaded oracle.  The `astpu_admission_*`/`astpu_degraded_step`
    series are scraped off the live shards by the PR 11 FleetCollector
    and fed to the declared SLO engine; the verdict rides the report."""
    os.environ["ASTPU_TELEMETRY"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
    from advanced_scrapper_tpu.net.rpc import RpcClient, RpcError
    from advanced_scrapper_tpu.obs import telemetry
    from advanced_scrapper_tpu.obs.collector import FleetCollector
    from advanced_scrapper_tpu.obs.slo import SloEngine

    rng = random.Random(f"overload-child|{seed}")
    n_batches = (OVERLOAD_DOCS + OVERLOAD_BATCH - 1) // OVERLOAD_BATCH
    kill_batch = rng.randrange(2, n_batches - 2)
    revive_batch = min(n_batches - 1, kill_batch + 2)
    kill_shard = rng.randrange(FLEET_SHARDS)

    port_list = _fleet_pick_ports(FLEET_SHARDS * FLEET_REPLICAS)
    ports = {
        (sid, rep): port_list[sid * FLEET_REPLICAS + rep]
        for sid in range(FLEET_SHARDS)
        for rep in range(FLEET_REPLICAS)
    }
    tight = (
        "--insert-rate", str(OVERLOAD_INSERT_RATE),
        "--max-inflight-inserts", "2",
    )
    procs: dict[tuple[int, int], subprocess.Popen] = {}
    stop_storm = threading.Event()
    storm_threads: list[threading.Thread] = []
    try:
        for sid in range(FLEET_SHARDS):
            for rep in range(FLEET_REPLICAS):
                procs[(sid, rep)] = _fleet_spawn_server(
                    case_dir, sid, rep, None, ports[(sid, rep)],
                    extra_args=tight, telemetry=True,
                    metrics_port_file=os.path.join(
                        case_dir, f"s{sid}n{rep}.mport"
                    ),
                )
        spec = FleetSpec(
            shards=tuple(
                tuple(
                    ("127.0.0.1", ports[(sid, rep)])
                    for rep in range(FLEET_REPLICAS)
                )
                for sid in range(FLEET_SHARDS)
            )
        )
        client = ShardedIndexClient(
            spec,
            space="bands",
            spill_dir=os.path.join(case_dir, "spill"),
            timeout=1.5,
            retries=1,
            health_checks=2,
            health_timeout=0.3,
            overload_budget=60.0,
        )
        _touch_marker(case_dir)

        # -- the storm: mixed-priority read/ping noise at ~10× the write
        # capacity, read-only so the admitted-work byte-equality stands
        def storm(wid: int):
            c = RpcClient(
                ("127.0.0.1", port_list[wid % len(port_list)]),
                timeout=1.0, retries=1, seed=1000 + wid,
            )
            k = 0
            probe_keys = np.stack(
                [_overload_doc_keys(i) for i in range(4)]
            ).ravel().astype(np.uint64)
            try:
                while not stop_storm.is_set():
                    k += 1
                    try:
                        if k % 3 == 0:
                            c.ping(timeout=0.5)  # the critical class
                        else:
                            c.call(
                                "probe", {"space": "bands"}, [probe_keys],
                                timeout=1.0,
                            )
                    except RpcError:
                        pass  # storm noise never fails the case by itself
                    time.sleep(0.02)
            finally:
                c.close()

        for w in range(OVERLOAD_STORM_WORKERS):
            t = threading.Thread(target=storm, args=(w,), daemon=True)
            t.start()
            storm_threads.append(t)

        ann: list[int] = []
        for b in range(n_batches):
            if b == kill_batch:
                # mid-storm SIGKILL of a REPLICA (rep 1 — never the
                # write target): the fleet observes a real death under
                # full overload and must STILL not promote
                os.kill(procs[(kill_shard, 1)].pid, signal.SIGKILL)
                procs[(kill_shard, 1)].wait()
            if b == revive_batch:
                procs[(kill_shard, 1)] = _fleet_spawn_server(
                    case_dir, kill_shard, 1, None, ports[(kill_shard, 1)],
                    extra_args=tight, telemetry=True,
                )
            rows = range(
                b * OVERLOAD_BATCH,
                min((b + 1) * OVERLOAD_BATCH, OVERLOAD_DOCS),
            )
            keys = np.stack([_overload_doc_keys(i) for i in rows])
            ids = client.allocate_doc_ids(len(keys))
            ann += np.asarray(client.check_and_add_batch(keys, ids)).tolist()
        stop_storm.set()
        for t in storm_threads:
            t.join(timeout=5)
        client.checkpoint()  # recovery probe: drains gap backfill

        # -- PR 11 integration: scrape the LIVE shards' admission series
        # and evaluate the declared overload SLO over the merged view
        endpoints = []
        for (sid, rep) in ports:
            mp = os.path.join(case_dir, f"s{sid}n{rep}.mport")
            if os.path.exists(mp):
                with open(mp) as f:
                    endpoints.append(
                        (f"s{sid}n{rep}", f"http://127.0.0.1:{f.read().strip()}")
                    )
        coll = FleetCollector(endpoints, timeout=2.0)
        coll.scrape_once()
        merged, _types = coll.merged_samples()
        slo = SloEngine(
            [
                {
                    "name": "reject_ratio_ceiling",
                    "kind": "ratio_max",
                    "metric": "astpu_admission_rejected_total",
                    "denominator": "astpu_admission_requests_total",
                    # shed hard, but never refuse everything: admitted
                    # work must keep flowing through the storm
                    "threshold": 0.97,
                },
            ]
        )
        verdict = slo.evaluate(merged)
        rejected = sum(
            v for name, _l, v in merged
            if name == "astpu_admission_rejected_total"
        )
        degraded_step = max(
            [v for name, _l, v in merged if name == "astpu_degraded_step"]
            or [0.0],
        )
        honored_s = sum(
            m.value
            for m in telemetry.REGISTRY.find(
                "astpu_rpc_overload_backoff_seconds_total"
            )
        )
        report = {
            "kill_shard": kill_shard,
            "kill_batch": kill_batch,
            "annotations": ann,
            "failovers": float(client._m_failovers.value),
            "promotions": float(client._m_promotions.value),
            "spilled": float(client._m_spilled.value),
            "degraded": float(client._m_degraded.value),
            "overload_backoff": float(client._m_overload.value),
            "slow_backoff": float(client._m_slow.value),
            "retry_after_honored_s": honored_s,
            "server_rejects": rejected,
            "degraded_step": degraded_step,
            "spill_pending": sum(
                int(k.size)
                for sh in client._shards
                for (_r, k, _d) in sh.pending
            ),
            "slo_ok": bool(verdict["ok"]),
            "slo_reject_ratio": verdict["objectives"][0]["value"],
            "write_targets": [
                sh.write_target for sh in client._shards
            ],
        }
        client.close()
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            os.path.join(case_dir, "overload_report.json"),
            json.dumps(report).encode(),
        )
        return 0
    finally:
        stop_storm.set()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def child_bitrot(case_dir: str, seed: int) -> int:
    """Silent-corruption healing loop on a live fleet.

    Mid-stream, one seeded bit of a REPLICA's on-disk segment is flipped
    in place — the medium lied: no error, no short write, no crash.  The
    ``scrub`` RPC must then detect the rot (block CRCs + whole-file
    digest), quarantine the poisoned segment server-side, and ONE
    anti-entropy repair pass must heal the withdrawn postings back from
    the healthy peer — with the stream's dedup annotations staying
    byte-equal to the uncorrupted single-node oracle throughout.  The
    fleet corpus/oracle are reused verbatim: bitrot must be invisible in
    the data plane, so the truth it is checked against is unchanged."""
    os.environ["ASTPU_TELEMETRY"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
    from advanced_scrapper_tpu.index.remote import RemoteIndex

    rng = random.Random(f"bitrot-child|{seed}")
    rot_shard = rng.randrange(FLEET_SHARDS)
    n_batches = (FLEET_DOCS + FLEET_BATCH - 1) // FLEET_BATCH
    # late enough that the replica holds real segments, early enough that
    # post-heal batches keep probing the healed ranges
    rot_batch = rng.randrange(3, n_batches - 1)

    port_list = _fleet_pick_ports(FLEET_SHARDS * FLEET_REPLICAS)
    ports = {
        (sid, rep): port_list[sid * FLEET_REPLICAS + rep]
        for sid in range(FLEET_SHARDS)
        for rep in range(FLEET_REPLICAS)
    }
    procs: dict[tuple[int, int], subprocess.Popen] = {}
    try:
        for sid in range(FLEET_SHARDS):
            for rep in range(FLEET_REPLICAS):
                procs[(sid, rep)] = _fleet_spawn_server(
                    case_dir, sid, rep, None, ports[(sid, rep)]
                )
        spec = FleetSpec(
            shards=tuple(
                tuple(
                    ("127.0.0.1", ports[(sid, rep)])
                    for rep in range(FLEET_REPLICAS)
                )
                for sid in range(FLEET_SHARDS)
            )
        )
        client = ShardedIndexClient(
            spec,
            space="bands",
            spill_dir=os.path.join(case_dir, "spill"),
            timeout=2.0,
            retries=1,
            health_checks=2,
            health_timeout=0.3,
        )
        _touch_marker(case_dir)
        ann: list[int] = []
        rot_extra: dict = {}
        for b in range(n_batches):
            if b == rot_batch:
                # the plant/detect/heal critical section sits BETWEEN
                # batches: no probe may run between the flip and the
                # repair, or a lazily-detected block would answer
                # "withdrawn" where the oracle answers "posted"
                remote = RemoteIndex(
                    ("127.0.0.1", ports[(rot_shard, 1)]),
                    space="bands", timeout=2.0, retries=1,
                )
                try:
                    # snapshot fence = a guaranteed cut, so the replica
                    # holds at least one immutable segment to rot
                    meta = remote.snapshot_meta()
                    segs = sorted(
                        f["name"] for f in meta["files"]
                        if f["name"].endswith(".seg")
                    )
                    if not segs:
                        raise RuntimeError("no live segment to corrupt")
                    victim = rng.choice(segs)
                    vpath = os.path.join(
                        case_dir, f"s{rot_shard}n1", "bands", victim
                    )
                    bit = rng.randrange(os.path.getsize(vpath) * 8)
                    with open(vpath, "r+b") as fh:
                        fh.seek(bit // 8)
                        byte = fh.read(1)[0]
                        fh.seek(bit // 8)
                        fh.write(bytes([byte ^ (1 << (bit % 8))]))
                    scrub_report = remote.scrub()["bands"]
                finally:
                    remote.close()
                heal = {"pushed": 0, "rounds": 0}
                for _ in range(3):
                    stats = client.repair_once()
                    heal["pushed"] += stats["pushed"]
                    heal["rounds"] += 1
                    if not stats["unmatched"]:
                        break
                rot_extra = {
                    "rot_shard": rot_shard,
                    "rot_batch": rot_batch,
                    "victim": victim,
                    "flipped_bit": bit,
                    "scrub_corrupt": scrub_report["corrupt"],
                    "repair": heal,
                }
            rows = range(
                b * FLEET_BATCH, min((b + 1) * FLEET_BATCH, FLEET_DOCS)
            )
            keys = np.stack([_fleet_doc_keys(i) for i in rows])
            ids = client.allocate_doc_ids(len(keys))
            ann += np.asarray(client.check_and_add_batch(keys, ids)).tolist()
        client.checkpoint()
        report = {
            "annotations": ann,
            "repair_rounds": float(client._m_repair_rounds.value),
            "repair_postings": float(client._m_repair_postings.value),
            **rot_extra,
        }
        client.close()
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            os.path.join(case_dir, "bitrot_report.json"),
            json.dumps(report).encode(),
        )
        return 0
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


CHILDREN = {
    "harvest": child_harvest,
    "scrape": child_scrape,
    "stream": child_stream,
    "pindex": child_pindex,
    "fleet": child_fleet,
    "tenant": child_tenant,
    "reshard": child_reshard,
    "overload": child_overload,
    "graph": child_graph,
    "bitrot": child_bitrot,
}


# -- verification ------------------------------------------------------------

def _expected_shard_text(prefix: str) -> str:
    from bs4 import BeautifulSoup

    page = f"<html><body><pre>{synth_cdx_text(prefix)}</pre></body></html>"
    return BeautifulSoup(page, "html.parser").get_text(separator="\n", strip=True)


def check_harvest_safety(case_dir: str) -> list[str]:
    """Kill-point invariant: every shard checkpoint on disk is
    byte-complete (equal to its deterministic expected content) or absent."""
    problems = []
    shard_dir = os.path.join(case_dir, "shards")
    if not os.path.isdir(shard_dir):
        return problems
    for name in os.listdir(shard_dir):
        if not name.endswith(".txt") or ".tmp-" in name:
            continue
        prefix = name[len("yahoo_"):-len(".txt")]
        got = open(os.path.join(shard_dir, name), encoding="utf-8").read()
        if got != _expected_shard_text(prefix):
            problems.append(f"torn shard checkpoint {name}")
    return problems


def verify_harvest(case_dir: str) -> list[str]:
    import pandas as pd

    problems = check_harvest_safety(case_dir)
    shard_dir = os.path.join(case_dir, "shards")
    for a in SWEEP_CHARS:
        for b in SWEEP_CHARS:
            if not os.path.exists(os.path.join(shard_dir, f"yahoo_{a}{b}.txt")):
                problems.append(f"shard {a}{b} never completed")
    out_csv = os.path.join(case_dir, "yfin_urls.csv")
    if not os.path.exists(out_csv):
        return problems + ["output csv missing"]
    urls = pd.read_csv(out_csv)["url"].astype(str).tolist()
    if set(urls) != harvest_expected_urls():
        problems.append(
            f"merged url set wrong: {len(urls)} rows, "
            f"missing={len(harvest_expected_urls() - set(urls))}, "
            f"extra={len(set(urls) - harvest_expected_urls())}"
        )
    if len(urls) != len(set(urls)):
        problems.append("duplicate urls in merged output")
    return problems


def check_stream_safety(case_dir: str) -> list[str]:
    """Kill-point invariant: the npz checkpoint target is loadable or
    absent (tmps are allowed to be torn — readers never look at them)."""
    import numpy as np

    ckpt = os.path.join(case_dir, "stream_index.npz")
    if not os.path.exists(ckpt):
        return []
    try:
        with np.load(ckpt) as data:
            _ = data["fingerprint"]
        return []
    except Exception as e:
        return [f"torn stream-index checkpoint: {e}"]


def verify_scrape(case_dir: str) -> list[str]:
    from advanced_scrapper_tpu.storage.csvio import read_url_column

    urls = read_url_column(os.path.join(case_dir, "urls.csv"))
    ok = read_url_column(
        os.path.join(case_dir, "success_articles_yfin.csv"), repair=True
    )
    bad = read_url_column(
        os.path.join(case_dir, "failed_articles_yfin.csv"), repair=True
    )
    problems = []
    if len(urls) != SCRAPE_URLS:
        problems.append(f"input csv damaged: {len(urls)} urls")
    if set(ok) | set(bad) != set(urls):
        missing = set(urls) - set(ok) - set(bad)
        problems.append(f"{len(missing)} urls lost: {sorted(missing)[:3]}")
    if len(ok) != len(set(ok)):
        problems.append("duplicate rows in success csv")
    if bad:
        problems.append(f"{len(bad)} unexpected failures: {bad[:3]}")
    return problems


def verify_stream(case_dir: str) -> list[str]:
    from advanced_scrapper_tpu.storage.csvio import read_url_column

    problems = check_stream_safety(case_dir)
    keys = read_url_column(
        os.path.join(case_dir, "stream_annotations.csv"), column="url",
        repair=True,
    )
    expect = {f"L{i}" for i in range(STREAM_DOCS)}
    if set(keys) != expect:
        problems.append(
            f"docs lost/invented: missing={sorted(expect - set(keys))[:3]} "
            f"extra={sorted(set(keys) - expect)[:3]}"
        )
    if len(keys) != len(set(keys)):
        problems.append("doc annotated twice")
    return problems


def check_pindex_safety(case_dir: str) -> list[str]:
    """Kill-point invariant: the persistent index OPENS from whatever the
    crash left (manifest whole-or-previous, orphans swept, WAL torn tail
    dropped) and holds no duplicated posting."""
    pdir = os.path.join(case_dir, "pindex")
    if not os.path.isdir(pdir):
        return []
    from advanced_scrapper_tpu.index import PersistentIndex

    try:
        # read_only: the checker must OBSERVE the kill-point state, not
        # repair it (and must never sweep a directory it does not own)
        idx = PersistentIndex(pdir, read_only=True)
    except Exception as e:
        return [f"index unopenable at kill point: {e}"]
    try:
        keys, _docs = idx.dump_postings()
        if len(keys) != len(set(keys.tolist())):
            return ["duplicated postings at kill point"]
    finally:
        idx.close()
    return []


def verify_pindex(case_dir: str) -> list[str]:
    """Convergence: after the clean resume, the live posting-key set equals
    the never-killed oracle's — every done marker, every kept doc's band
    keys, nothing lost, nothing duplicated."""
    problems = check_pindex_safety(case_dir)
    from advanced_scrapper_tpu.index import PersistentIndex

    idx = PersistentIndex(os.path.join(case_dir, "pindex"), read_only=True)
    try:
        keys, _docs = idx.dump_postings()
    finally:
        idx.close()
    got = set(keys.tolist())
    expect: set[int] = set()
    for i in range(PINDEX_DOCS):
        expect.add(_pindex_done_key(i))
        if not (i % 7 == 3 and i >= 3):  # planted dups post no band keys
            expect.update(int(k) for k in _pindex_doc_keys(i))
    if got != expect:
        problems.append(
            f"postings lost/invented: missing={len(expect - got)} "
            f"extra={len(got - expect)}"
        )
    if len(keys) != len(got):
        problems.append("duplicated postings after resume")
    return problems


def _check_shard_postings(
    case_dir: str,
    oracle_minmap: dict,
    *,
    num_shards: int = FLEET_SHARDS,
    replicas: int = FLEET_REPLICAS,
    vnodes: int = 64,
    allow_superseded: bool = False,
) -> list[str]:
    """Per shard, the union of its node indexes must hold exactly the
    oracle's posting keys for that shard's ring slice with identical min
    doc ids — zero lost, zero duplicated (each node also checked
    individually for duplicate keys: a duplicate is a double-applied
    retry).  Shared by the fleet, bitrot and reshard verifiers — the
    reshard one passes the POST-cutover ring, so the check doubles as
    proof every migrated posting landed on its new owner and nowhere
    else (handed-off residue is excluded by ``dump_postings`` itself).

    ``allow_superseded`` relaxes the per-node shape for the dual-write
    window's documented artifact: a dual-applied write can land on the
    NEW owner before the migration stream delivers the same key's older
    posting, leaving a raw higher-doc posting the later arrival
    supersedes.  Min-doc attribution is untouched (still asserted
    exactly); only an exact ``(key, doc)`` pair applied twice — a true
    double-apply, which the server's semantic filter makes impossible
    for any single delivery — stays a problem."""
    import numpy as np

    from advanced_scrapper_tpu.index import PersistentIndex
    from advanced_scrapper_tpu.index.fleet import ring_assign

    problems: list[str] = []
    all_keys = np.array(sorted(oracle_minmap), dtype=np.uint64)
    shard_of = ring_assign(all_keys, num_shards, vnodes)
    for sid in range(num_shards):
        expect = {
            int(k): oracle_minmap[int(k)]
            for k in all_keys[shard_of == sid].tolist()
        }
        union: dict[int, int] = {}
        for rep in range(replicas):
            sdir = os.path.join(case_dir, f"s{sid}n{rep}", "bands")
            if not os.path.isdir(sdir):
                continue
            try:
                idx = PersistentIndex(sdir, read_only=True)
            except Exception as e:
                problems.append(f"shard s{sid}n{rep} unopenable: {e}")
                continue
            try:
                keys, docs = idx.dump_postings()
            finally:
                idx.close()
            pairs = list(zip(keys.tolist(), docs.tolist()))
            if allow_superseded:
                if len(pairs) != len(set(pairs)):
                    problems.append(
                        f"duplicated postings on s{sid}n{rep} "
                        f"(same (key, doc) pair applied twice)"
                    )
            elif len(keys) != len(set(keys.tolist())):
                problems.append(
                    f"duplicated postings on s{sid}n{rep} (double-applied retry)"
                )
            for k, d in pairs:
                if not allow_superseded and k in union and union[k] != d:
                    problems.append(
                        f"shard {sid} replicas disagree on key {k}: "
                        f"{union[k]} vs {d}"
                    )
                union[k] = min(union.get(k, d), d)
        if union != expect:
            missing = set(expect) - set(union)
            extra = set(union) - set(expect)
            wrong = {
                k for k in set(expect) & set(union) if expect[k] != union[k]
            }
            problems.append(
                f"shard {sid} postings lost/invented: missing={len(missing)} "
                f"extra={len(extra)} wrong_doc={len(wrong)}"
            )
    return problems


def verify_fleet(case_dir: str) -> list[str]:
    """Fleet convergence against the single-node oracle:

    - the child's dedup annotations are BYTE-identical to the oracle's;
    - per shard, the union of its node indexes holds exactly the oracle's
      posting keys for that shard's ring slice, with identical min doc
      ids — zero lost, zero duplicated (each node checked individually
      for duplicate keys: a duplicate is a double-applied retry);
    - the SIGKILLed primary's directory — frozen at its kill point —
      still opens read-only (manifest whole-or-previous, WAL torn tail
      dropped);
    - the spill journal fully replayed (``spill_pending == 0``) and the
      mode's failover/promotion/spill counters actually moved.
    """
    problems: list[str] = []
    report_path = os.path.join(case_dir, "fleet_report.json")
    if not os.path.exists(report_path):
        return ["fleet child never wrote its report (ingest died)"]
    with open(report_path) as f:
        report = json.load(f)

    oracle_ann, oracle_minmap = fleet_oracle_annotations()
    if report["annotations"] != oracle_ann:
        diff = [
            i for i, (a, b) in enumerate(zip(report["annotations"], oracle_ann))
            if a != b
        ]
        problems.append(
            f"annotations diverge from the single-node oracle at docs "
            f"{diff[:5]} (of {len(diff)})"
        )

    problems += _check_shard_postings(case_dir, oracle_minmap)

    if report.get("spill_pending"):
        problems.append(
            f"{report['spill_pending']} spilled postings never replayed"
        )
    mode = report.get("mode")
    if mode in ("insert", "probe", "promotion") and not report.get("failovers"):
        problems.append(f"mode {mode}: the kill never caused a failover")
    if mode == "promotion":
        if not report.get("promotions"):
            problems.append("promotion mode: no promotion happened")
        if not report.get("spilled") or not report.get("replayed"):
            problems.append(
                "promotion mode: spill/replay counters never moved "
                f"(spilled={report.get('spilled')}, "
                f"replayed={report.get('replayed')})"
            )
    # observability-plane integration: the kill must be ATTRIBUTABLE from
    # the collector's harvested sidecars and the declared SLO alone
    if report.get("primary_died"):
        dead = [str(s) for s in report.get("dead_shards", [])]
        kill_names = {str(report.get("kill_shard")), f"s{report.get('kill_shard')}n0"}
        if not kill_names & set(dead):
            problems.append(
                f"harvested flight-recorder dumps never named the killed "
                f"shard {sorted(kill_names)} (got {dead})"
            )
        if report.get("slo_flipped_batch") is None:
            problems.append(
                "shards_healthy SLO never flipped although the primary died"
            )
        elif mode in ("insert", "probe", "promotion") and (
            report["slo_flipped_batch"] > report.get("kill_batch", 0) + 1
        ):
            problems.append(
                f"shards_healthy SLO flipped at batch "
                f"{report['slo_flipped_batch']}, more than one interval after "
                f"the kill at batch {report.get('kill_batch')}"
            )
    if not report.get("slo_final_ok", True):
        problems.append(
            "shards_healthy SLO still violated at sweep end (fleet never "
            "recovered a proven write target per shard)"
        )
    return problems


def verify_tenant(case_dir: str) -> list[str]:
    """Zero-leakage convergence for the tenant sweep:

    - each tenant's annotations AND final probe matrix are byte-identical
      to its own single-node oracle — a shard kill mid-mixed-traffic may
      slow a tenant down, never change its answers;
    - the cross-tenant isolation sweep saw zero hits (tenant A's keys
      are invisible under B, even across the failover window);
    - no spilled postings left pending.
    """
    problems: list[str] = []
    report_path = os.path.join(case_dir, "tenant_report.json")
    if not os.path.exists(report_path):
        return ["tenant child never wrote its report (gateway died)"]
    with open(report_path) as f:
        report = json.load(f)
    for t in TENANT_IDS:
        oracle_ann, oracle_probes = tenant_oracle(t)
        got_ann = report["annotations"].get(t)
        if got_ann != oracle_ann:
            diff = [
                i for i, (a, b) in enumerate(zip(got_ann or [], oracle_ann))
                if a != b
            ]
            problems.append(
                f"tenant {t}: annotations diverge from the single-node "
                f"oracle at docs {diff[:5]} (of {len(diff)})"
            )
        got_probes = report["probes"].get(t)
        if got_probes != oracle_probes:
            diff = [
                i for i, (a, b) in enumerate(zip(got_probes or [], oracle_probes))
                if a != b
            ]
            problems.append(
                f"tenant {t}: probe matrix diverges from the oracle at "
                f"docs {diff[:5]} (of {len(diff)})"
            )
    if report.get("isolation_violations"):
        problems.append(
            f"{report['isolation_violations']} cross-tenant probe hits — "
            "one tenant's postings leaked into another's answers"
        )
    if report.get("spill_pending"):
        problems.append(
            f"{report['spill_pending']} spilled postings never replayed"
        )
    return problems


def check_reshard_safety(case_dir: str) -> list[str]:
    """Kill-point invariant for the migration WAL: at any crash instant
    the ledger is absent or ONE whole, schema-valid document (atomic
    replace — a half-flipped range is unrepresentable on disk)."""
    from advanced_scrapper_tpu.index.reshard import ReshardLedger, ledger_path

    path = ledger_path(os.path.join(case_dir, "spill"), "bands")
    try:
        led = ReshardLedger.load(path)
    except Exception as e:
        return [f"reshard ledger torn or unrepresentable: {e}"]
    if led is not None and led.phase not in ("active", "done"):
        return [f"reshard ledger in unknown phase {led.phase!r}"]
    return []


def verify_reshard(case_dir: str) -> list[str]:
    """Elastic-cutover acceptance against the unresharded single-node
    oracle:

    - probe answers for every doc are byte-identical to the oracle's
      (min-doc attribution survived the migration);
    - the migration WAL is sealed (phase ``done``) with every range
      ``retired``, and the client ended routing on the new ring;
    - per NEW-ring shard, the node index holds exactly the oracle's
      postings for that slice — zero lost, zero duplicated — proving
      every migrated posting landed on its new owner and nowhere else;
    - the spill journal fully replayed, and the offline fsck reports
      every node directory clean (handed-off arcs are notes, not loss).
    """
    problems: list[str] = []
    report_path = os.path.join(case_dir, "reshard_report.json")
    if not os.path.exists(report_path):
        return ["reshard child never wrote its report (cutover died)"]
    with open(report_path) as f:
        report = json.load(f)

    oracle_probes, oracle_minmap = reshard_oracle()
    if report["probes"] != oracle_probes:
        diff = [
            i for i, (a, b) in enumerate(zip(report["probes"], oracle_probes))
            if a != b
        ]
        problems.append(
            f"probe answers diverge from the single-node oracle at docs "
            f"{diff[:5]} (of {len(diff)})"
        )
    if report.get("ledger_phase") != "done":
        problems.append(
            f"migration WAL never sealed (phase={report.get('ledger_phase')})"
        )
    if not report.get("all_retired"):
        problems.append("ranges left un-retired after the cutover finished")
    if report.get("route_shards") != RESHARD_SHARDS_NEW:
        problems.append(
            f"client ended routing on {report.get('route_shards')} shards, "
            f"not the new ring's {RESHARD_SHARDS_NEW}"
        )
    if report.get("spill_pending"):
        problems.append(
            f"{report['spill_pending']} spilled postings never replayed"
        )
    problems += _check_shard_postings(
        case_dir,
        oracle_minmap,
        num_shards=RESHARD_SHARDS_NEW,
        replicas=1,
        vnodes=RESHARD_VNODES,
        allow_superseded=True,
    )

    # the offline twin gets the last word: every node dir verifies clean
    import fsck_index

    node_dirs = [
        os.path.join(case_dir, f"s{sid}n0")
        for sid in range(RESHARD_SHARDS_NEW)
        if os.path.isdir(os.path.join(case_dir, f"s{sid}n0"))
    ]
    fsck_report = fsck_index.fsck(node_dirs)
    if not fsck_report["ok"]:
        problems += [f"fsck: {p}" for p in fsck_report["problems"]]
    return problems


def verify_overload(case_dir: str) -> list[str]:
    """Overload-storm acceptance: zero collapse, zero promotions,
    counted rejects with retry-after honored, no degraded probes, and
    admitted-work annotations byte-equal to the unloaded oracle."""
    problems: list[str] = []
    report_path = os.path.join(case_dir, "overload_report.json")
    if not os.path.exists(report_path):
        return ["overload child never wrote its report (storm collapsed)"]
    with open(report_path) as f:
        report = json.load(f)

    oracle_ann, _minmap = overload_oracle_annotations()
    if report["annotations"] != oracle_ann:
        diff = [
            i for i, (a, b) in enumerate(zip(report["annotations"], oracle_ann))
            if a != b
        ]
        problems.append(
            f"admitted-work annotations diverge from the UNLOADED oracle at "
            f"docs {diff[:5]} (of {len(diff)}) — overload changed semantics"
        )
    if report.get("promotions"):
        problems.append(
            f"{report['promotions']} promotions under overload — a healthy "
            "write target lost its seat (overload treated as death)"
        )
    if not report.get("failovers"):
        problems.append(
            "the mid-storm replica SIGKILL was never observed (the case "
            "did not exercise death-under-overload)"
        )
    if not report.get("server_rejects"):
        problems.append("the storm never tripped a counted admission reject")
    if report.get("server_rejects") and not report.get("retry_after_honored_s"):
        problems.append("rejects happened but no retry-after was ever honored")
    if report.get("degraded"):
        problems.append(
            f"{report['degraded']} probe rows answered degraded — overload "
            "leaked into the data plane"
        )
    if report.get("spill_pending"):
        problems.append(
            f"{report['spill_pending']} spilled postings never replayed"
        )
    if not report.get("slo_ok", True):
        problems.append(
            f"declared reject-ratio SLO violated "
            f"(ratio={report.get('slo_reject_ratio')})"
        )
    if any(wt != 0 for wt in report.get("write_targets", [])):
        problems.append(
            f"write targets moved under the storm: {report['write_targets']}"
        )
    return problems


def verify_bitrot(case_dir: str) -> list[str]:
    """Bitrot acceptance: the planted flip was DETECTED by scrub (never
    served), the poisoned segment was quarantined (sidecar evidence on
    the corrupted node), repair healed the withdrawn postings from the
    healthy peer (per-shard unions equal the oracle), annotations stayed
    byte-equal to the uncorrupted single-node oracle, and the offline
    fsck reports every node directory clean afterwards."""
    problems: list[str] = []
    report_path = os.path.join(case_dir, "bitrot_report.json")
    if not os.path.exists(report_path):
        return ["bitrot child never wrote its report (ingest died)"]
    with open(report_path) as f:
        report = json.load(f)

    oracle_ann, oracle_minmap = fleet_oracle_annotations()
    if report["annotations"] != oracle_ann:
        diff = [
            i for i, (a, b) in enumerate(zip(report["annotations"], oracle_ann))
            if a != b
        ]
        problems.append(
            f"annotations diverge from the uncorrupted oracle at docs "
            f"{diff[:5]} (of {len(diff)}) — the flipped bit leaked into "
            "the data plane"
        )
    if not report.get("scrub_corrupt"):
        problems.append(
            f"scrub never detected the planted flip in {report.get('victim')}"
        )
    if not report.get("repair", {}).get("pushed"):
        problems.append(
            "repair pushed nothing — the quarantined postings were never "
            "healed from the healthy peer"
        )
    rot_dir = os.path.join(case_dir, f"s{report.get('rot_shard', 0)}n1", "bands")
    if os.path.isdir(rot_dir) and not any(
        n.endswith(".quarantine") for n in os.listdir(rot_dir)
    ):
        problems.append(
            "no .quarantine sidecar on the corrupted node — the poisoned "
            "segment was dropped without preserving the evidence"
        )
    problems += _check_shard_postings(case_dir, oracle_minmap)

    # the offline twin gets the last word: every node dir verifies clean
    import fsck_index

    node_dirs = [
        os.path.join(case_dir, f"s{sid}n{rep}")
        for sid in range(FLEET_SHARDS)
        for rep in range(FLEET_REPLICAS)
        if os.path.isdir(os.path.join(case_dir, f"s{sid}n{rep}"))
    ]
    fsck_report = fsck_index.fsck(node_dirs)
    if not fsck_report["ok"]:
        problems += [f"fsck: {p}" for p in fsck_report["problems"]]
    return problems


def check_graph_safety(case_dir: str) -> list[str]:
    """Kill-point invariants for the stage-graph workload: the annotations
    CSV parses (torn tails are the reader's repair problem, never a loss),
    and IF a chaos fault dumped the flight recorder, the sidecar holds a
    whole-graph drain snapshot — stage in-flight items and edge depths at
    the instant of death (the runtime's drain-on-crash contract)."""
    problems: list[str] = []
    flight = os.path.join(case_dir, "flight.jsonl")
    if os.path.exists(flight):
        summaries, snaps = [], []
        with open(flight, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # an OS-cut tail line is allowed
                if ev.get("kind") != "snapshot":
                    continue
                if ev.get("name") == "graphs":
                    summaries.append(ev)
                elif ev.get("name") == "graph":
                    snaps.append(ev)
        if not summaries:
            problems.append(
                "chaos fault dumped the flight recorder but the runtime's "
                "snapshot hook never ran"
            )
        elif any(s.get("live", 0) > 0 for s in summaries):
            # a graph WAS live at the fault: its whole-graph state must be
            # in the dump (a pre-start fault legitimately has live=0)
            ours = [s for s in snaps if s.get("graph") == "crashsweep_graph"]
            if not ours:
                problems.append(
                    "graph was live at the fault but no whole-graph drain "
                    "snapshot landed in the dump"
                )
            elif "edges" not in ours[-1] or "stages" not in ours[-1]:
                problems.append(f"graph snapshot missing edges/stages: {ours[-1]}")
    return problems


def verify_graph(case_dir: str) -> list[str]:
    from advanced_scrapper_tpu.storage.csvio import read_url_column

    problems = check_graph_safety(case_dir)
    keys = read_url_column(
        os.path.join(case_dir, "graph_annotations.csv"), column="url",
        repair=True,
    )
    expect = {f"G{i}" for i in range(GRAPH_DOCS)}
    if set(keys) != expect:
        problems.append(
            f"records lost/invented: missing={sorted(expect - set(keys))[:3]} "
            f"extra={sorted(set(keys) - expect)[:3]}"
        )
    if len(keys) != len(set(keys)):
        problems.append("record persisted twice")
    return problems


SAFETY_CHECKS = {
    "harvest": check_harvest_safety,
    "stream": check_stream_safety,
    "pindex": check_pindex_safety,
    "reshard": check_reshard_safety,
    "graph": check_graph_safety,
}
VERIFIERS = {
    "harvest": verify_harvest,
    "scrape": verify_scrape,
    "stream": verify_stream,
    "pindex": verify_pindex,
    "fleet": verify_fleet,
    "tenant": verify_tenant,
    "reshard": verify_reshard,
    "overload": verify_overload,
    "graph": verify_graph,
    "bitrot": verify_bitrot,
}

#: chaos specs that land the pindex kill-points INSIDE each durability
#: mechanism: the WAL append, the segment-cut atomic write, and the
#: cut/compaction manifest swap (`only=` scopes injection by substring)
PINDEX_CHAOS_TARGETS = ("wal-", "seg-", "manifest.json")


# -- parent driver -----------------------------------------------------------

def _spawn(workload: str, case_dir: str, seed: int, chaos: str | None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ASTPU_CHAOS_FS", None)
    if chaos:
        env["ASTPU_CHAOS_FS"] = chaos
    log = open(os.path.join(case_dir, "child.log"), "ab")
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            workload,
            "--dir",
            case_dir,
            "--seed",
            str(seed),
        ],
        env=env,
        cwd=REPO,
        stdout=log,
        stderr=log,
    )
    log.close()
    return proc


def prepare_case(workload: str, case_dir: str) -> None:
    os.makedirs(case_dir, exist_ok=True)
    if workload == "scrape":
        path = os.path.join(case_dir, "urls.csv")
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("url\n")
                for i in range(SCRAPE_URLS):
                    f.write(f"https://x/news/doc{i}.html\n")


def run_case(
    workload: str,
    case_dir: str,
    seed: int,
    kill_after: float | None,
    chaos: str | None = None,
    timeout: float = 180.0,
) -> dict:
    """One sweep case: (optionally killed/chaos) run, kill-point safety
    check, then a clean run to completion, then full verification."""
    prepare_case(workload, case_dir)
    marker = os.path.join(case_dir, MARKER)
    if os.path.exists(marker):
        os.unlink(marker)
    record: dict = {
        "workload": workload,
        "seed": seed,
        "kill_after": kill_after,
        "chaos": chaos,
    }

    proc = _spawn(workload, case_dir, seed, chaos)
    if kill_after is not None:
        deadline = time.monotonic() + timeout
        while not os.path.exists(marker) and proc.poll() is None:
            if time.monotonic() > deadline:
                proc.kill()
                proc.wait()
                record["problems"] = ["child never signalled work start"]
                return record
            time.sleep(0.005)
        time.sleep(kill_after)
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
            record["killed"] = True
        else:
            record["killed"] = False
            record["early_rc"] = proc.returncode
    else:
        proc.wait(timeout=timeout)
        # chaos mode: exit=1 hard-exits with 73 at a seeded write; an
        # injected EIO the workload cannot contain also dies mid-run —
        # both are crash instants the restart must heal
        record["killed"] = proc.returncode != 0
        record["early_rc"] = proc.returncode

    record["safety"] = SAFETY_CHECKS.get(workload, lambda d: [])(case_dir)

    # clean restart: resume must converge with no chaos and no kill
    clean = _spawn(workload, case_dir, seed, None)
    clean.wait(timeout=timeout)
    record["resume_rc"] = clean.returncode
    problems = list(record["safety"])
    if clean.returncode != 0:
        problems.append(f"resume run exited {clean.returncode}")
    problems += VERIFIERS[workload](case_dir)
    record["problems"] = problems
    return record


def sweep_workload(
    workload: str,
    base_dir: str,
    *,
    sigkills: int,
    chaos_kills: int = 0,
    seed: int = 0,
    kill_window: tuple[float, float] = (0.03, 0.6),
    chaos_only: tuple[str, ...] | None = None,
) -> dict:
    """Seeded sweep of one workload: ``sigkills`` wall-clock SIGKILL
    instants plus ``chaos_kills`` in-write ``os._exit`` crash points.

    ``chaos_only`` scopes each chaos case's injection to one path
    substring, cycling through the tuple — how the pindex sweep aims its
    kill-points inside WAL appends, segment cuts and manifest swaps."""
    rng = random.Random(f"crashsweep|{workload}|{seed}")
    cases = []
    for i in range(sigkills):
        delay = rng.uniform(*kill_window)
        # a draw past the end of the work window kills nothing — retry the
        # case with a shrunken delay (fresh dir) so the sweep reliably
        # lands its budgeted number of kill instants
        for attempt in range(3):
            suffix = f"-t{attempt}" if attempt else ""
            rec = run_case(
                workload,
                os.path.join(base_dir, f"{workload}-k{i}{suffix}"),
                seed=seed * 1000 + i,
                kill_after=delay,
            )
            if rec.get("killed") or rec["problems"]:
                break
            delay *= 0.4
        cases.append(rec)
    for i in range(chaos_kills):
        spec = f"seed={seed * 100 + i},crash=0.08,short_write=0.03,exit=1"
        if chaos_only:
            target = chaos_only[i % len(chaos_only)]
            # targeted: fault the ONE mechanism hard so a kill actually
            # lands inside it (the untargeted rates are tuned for runs
            # that touch thousands of files; a scoped run touches few)
            spec = f"seed={seed * 100 + i},crash=0.25,short_write=0.1,exit=1,only={target}"
        cases.append(
            run_case(
                workload,
                os.path.join(base_dir, f"{workload}-c{i}"),
                seed=seed * 1000 + 500 + i,
                kill_after=None,
                chaos=spec,
            )
        )
    return {
        "workload": workload,
        "cases": cases,
        "kills": sum(1 for c in cases if c.get("killed")),
        "problems": [p for c in cases for p in c.get("problems", [])],
    }


def sweep_overload(base_dir: str, *, kills: int, seed: int = 0) -> dict:
    """Seeded overload sweep: each case storms a fresh admission-tight
    fleet at ≥10× capacity with a mid-storm replica SIGKILL, then
    verifies the zero-collapse/zero-promotion/byte-equality contract.
    A 'kill landed' = the client watched the replica die (failovers
    moved) WITHOUT any promotion."""
    cases = []
    for i in range(kills):
        case_seed = seed * 1000 + i
        case_dir = os.path.join(base_dir, f"overload-k{i}")
        os.makedirs(case_dir, exist_ok=True)
        rec: dict = {"workload": "overload", "seed": case_seed}
        proc = _spawn("overload", case_dir, case_seed, None)
        try:
            proc.wait(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            rec["problems"] = ["overload child hung past 240 s"]
            cases.append(rec)
            continue
        problems = []
        if proc.returncode != 0:
            problems.append(f"overload child exited {proc.returncode}")
        problems += verify_overload(case_dir)
        report_path = os.path.join(case_dir, "overload_report.json")
        killed = False
        if os.path.exists(report_path):
            with open(report_path) as f:
                r = json.load(f)
            killed = bool(r.get("failovers")) and not r.get("promotions")
            rec["counters"] = {
                k: r.get(k)
                for k in (
                    "failovers", "promotions", "server_rejects",
                    "retry_after_honored_s", "degraded_step",
                )
            }
        rec["killed"] = killed
        rec["problems"] = problems
        cases.append(rec)
    return {
        "workload": "overload",
        "cases": cases,
        "kills": sum(1 for c in cases if c.get("killed")),
        "problems": [p for c in cases for p in c.get("problems", [])],
    }


def sweep_fleet(base_dir: str, *, kills: int, seed: int = 0) -> dict:
    """Seeded fleet sweep: each case runs the fleet child ONCE (the
    client survives its shard-primary kills and carries the stream to
    completion — restart-and-resume is the SHARD's story, exercised by
    the respawn inside the case), then verifies byte-convergence against
    the single-node oracle.  The kill mechanism cycles through
    ``FLEET_KILL_MODES`` via the case seed."""
    cases = []
    for i in range(kills):
        case_seed = seed * 1000 + i
        case_dir = os.path.join(base_dir, f"fleet-k{i}")
        os.makedirs(case_dir, exist_ok=True)
        rec: dict = {
            "workload": "fleet",
            "seed": case_seed,
            "mode": FLEET_KILL_MODES[case_seed % len(FLEET_KILL_MODES)],
        }
        proc = _spawn("fleet", case_dir, case_seed, None)
        try:
            proc.wait(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            rec["problems"] = ["fleet child hung past 240 s"]
            cases.append(rec)
            continue
        problems = []
        if proc.returncode != 0:
            problems.append(f"fleet child exited {proc.returncode}")
        problems += verify_fleet(case_dir)
        report_path = os.path.join(case_dir, "fleet_report.json")
        killed = False
        if os.path.exists(report_path):
            with open(report_path) as f:
                r = json.load(f)
            # a kill "landed" iff the client actually watched a node die
            killed = bool(r.get("failovers") or r.get("degraded"))
            rec["counters"] = {
                k: r.get(k)
                for k in ("failovers", "promotions", "spilled", "replayed",
                          "degraded")
            }
        rec["killed"] = killed
        rec["problems"] = problems
        cases.append(rec)
    return {
        "workload": "fleet",
        "cases": cases,
        "kills": sum(1 for c in cases if c.get("killed")),
        "problems": [p for c in cases for p in c.get("problems", [])],
    }


def sweep_tenant(base_dir: str, *, kills: int, seed: int = 0) -> dict:
    """Seeded tenant sweep: each case runs the tenant child ONCE (the
    shard-primary SIGKILL is internal, landed mid mixed two-tenant
    traffic), then verifies per-tenant byte-convergence against the
    single-node oracles and the zero-leakage contract.  A 'kill landed'
    = at least one per-tenant fleet sibling actually watched the node
    die (failovers moved)."""
    cases = []
    for i in range(kills):
        case_seed = seed * 1000 + i
        case_dir = os.path.join(base_dir, f"tenant-k{i}")
        os.makedirs(case_dir, exist_ok=True)
        rec: dict = {"workload": "tenant", "seed": case_seed}
        proc = _spawn("tenant", case_dir, case_seed, None)
        try:
            proc.wait(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            rec["problems"] = ["tenant child hung past 240 s"]
            cases.append(rec)
            continue
        problems = []
        if proc.returncode != 0:
            problems.append(f"tenant child exited {proc.returncode}")
        problems += verify_tenant(case_dir)
        report_path = os.path.join(case_dir, "tenant_report.json")
        killed = False
        if os.path.exists(report_path):
            with open(report_path) as f:
                r = json.load(f)
            killed = bool(r.get("failovers"))
            rec["counters"] = {
                k: r.get(k)
                for k in ("failovers", "promotions", "spill_pending",
                          "isolation_violations")
            }
        rec["killed"] = killed
        rec["problems"] = problems
        cases.append(rec)
    return {
        "workload": "tenant",
        "cases": cases,
        "kills": sum(1 for c in cases if c.get("killed")),
        "problems": [p for c in cases for p in c.get("problems", [])],
    }


def sweep_bitrot(base_dir: str, *, kills: int, seed: int = 0) -> dict:
    """Seeded bitrot sweep: each case streams the fleet corpus with a
    seeded mid-stream silent bit flip planted in a replica's segment,
    then verifies the detect→quarantine→heal→byte-equality contract plus
    a clean offline fsck.  A 'kill landed' = the scrub actually caught
    the planted flip (every case plants one)."""
    cases = []
    for i in range(kills):
        case_seed = seed * 1000 + i
        case_dir = os.path.join(base_dir, f"bitrot-k{i}")
        os.makedirs(case_dir, exist_ok=True)
        rec: dict = {"workload": "bitrot", "seed": case_seed}
        proc = _spawn("bitrot", case_dir, case_seed, None)
        try:
            proc.wait(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            rec["problems"] = ["bitrot child hung past 240 s"]
            cases.append(rec)
            continue
        problems = []
        if proc.returncode != 0:
            problems.append(f"bitrot child exited {proc.returncode}")
        problems += verify_bitrot(case_dir)
        report_path = os.path.join(case_dir, "bitrot_report.json")
        detected = False
        if os.path.exists(report_path):
            with open(report_path) as f:
                r = json.load(f)
            detected = bool(r.get("scrub_corrupt"))
            rec["counters"] = {
                "victim": r.get("victim"),
                "scrub_corrupt": len(r.get("scrub_corrupt", [])),
                "repair_pushed": r.get("repair", {}).get("pushed"),
            }
        rec["killed"] = detected
        rec["problems"] = problems
        cases.append(rec)
    return {
        "workload": "bitrot",
        "cases": cases,
        "kills": sum(1 for c in cases if c.get("killed")),
        "problems": [p for c in cases for p in c.get("problems", [])],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", choices=sorted(CHILDREN), default=None)
    ap.add_argument("--dir", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kills", type=int, default=26, help="total kill instants")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args(argv)

    if args.child:
        return CHILDREN[args.child](args.dir, args.seed)

    import tempfile

    base = args.dir or tempfile.mkdtemp(prefix="crashsweep-")
    per = max(1, args.kills // 10)
    report = {
        "seed": args.seed,
        "workloads": [
            sweep_workload(
                "harvest", base, sigkills=per - 1, chaos_kills=1, seed=args.seed
            ),
            sweep_workload(
                "scrape", base, sigkills=per - 1, chaos_kills=1, seed=args.seed
            ),
            sweep_workload(
                "pindex",
                base,
                sigkills=max(1, per - 3),
                chaos_kills=3,
                seed=args.seed,
                chaos_only=PINDEX_CHAOS_TARGETS,
            ),
            sweep_fleet(base, kills=per, seed=args.seed),
            sweep_tenant(base, kills=per, seed=args.seed),
            sweep_workload(
                "reshard",
                base,
                sigkills=max(1, per - 1),
                chaos_kills=1,
                seed=args.seed,
                # the post-marker window spans inserts AND the cutover
                kill_window=(0.05, 1.5),
                chaos_only=("reshard-wal",),
            ),
            sweep_overload(base, kills=per, seed=args.seed),
            sweep_bitrot(base, kills=per, seed=args.seed),
            sweep_workload(
                "graph",
                base,
                sigkills=max(1, per - 2),
                chaos_kills=2,
                seed=args.seed,
            ),
            sweep_workload(
                "stream",
                base,
                # the remainder: nine workloads above each land exactly
                # `per` instants, stream takes what's left of --kills
                # (its one chaos case included)
                sigkills=max(1, args.kills - 9 * per - 1),
                chaos_kills=1,
                seed=args.seed,
                kill_window=(0.05, 1.2),
            ),
        ],
    }
    report["kills"] = sum(w["kills"] for w in report["workloads"])
    report["problems"] = [p for w in report["workloads"] for p in w["problems"]]
    report["ok"] = not report["problems"]
    out = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
    print(out)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
