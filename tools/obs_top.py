"""obs_top — live terminal dashboard over a telemetry status endpoint.

Points at any process serving the observability pair (``GET /status`` +
``GET /metrics``): the control plane (``net/control.py``), a lease server's
mirror, or a bench run under ``ASTPU_TELEMETRY=1`` (which prints its
endpoint address to stderr at start).

Two modes:

- ``--once``: fetch one ``/status`` snapshot and print the full frame
  (per-stage latency table, queue/arena gauges, dedup + fleet counters) —
  the scriptable/smoke-testable path.
- ``--graph`` (combinable with ``--once``): the stage-graph runtime view —
  every live graph with its edges (depth/capacity, items in/out, put/get
  stall seconds) and stages (throughput, busy time), straight from the
  scheduler's own ``astpu_edge_*`` / ``astpu_stage_*`` series.
- ``--fleet`` (combinable with ``--once``): the fleet view — point
  ``--url`` at a metrics collector (``tools/obs_fleet.py``) for
  per-process endpoint health/staleness, per-instance headline rates,
  harvested crash sidecars (which shard died), and SLO verdicts.
- ``--prof`` (combinable with ``--once``): the continuous-profiler view —
  fetch ``GET /profile`` (a process under ``ASTPU_PROFILE``, or a
  collector's merged fleet view) and render the hottest folded stacks
  with sample shares (``--prof-top`` rows).
- ``--quality`` (combinable with ``--once``): the quality view — the
  decision mix (which tier settled each verdict, from the always-on
  ``astpu_decision_total`` counters, with per-tier rates in live mode),
  the canary prober's ground-truth SLIs (``astpu_canary_recall`` /
  ``_precision``, round latency and cadence) and the canary SLO
  verdicts; the sticky line tracks recall/precision plus compliance.
- ``--tenants`` (combinable with ``--once``): the multi-tenant front-door
  view — per-tenant admit/reject/error rates and quota refusals by reason
  (``astpu_tenant_requests_total`` / ``_rejected_total``), key-space
  posting counts (``astpu_tenant_postings``), per-tenant verb p99 and the
  tenant SLO error-budget burn; the sticky line ranks the hottest tenants
  and flags violated tenant objectives.  Point ``--url`` at a gateway's
  metrics sidecar (``service/gateway.py --metrics-port``).
- live (default): the :class:`obs.console.ConsoleMux` idiom — a sticky
  one-line summary repainted in place (per-stage rates computed from
  successive histogram snapshots, queue depths, fleet health) with notable
  transitions (fault injections, quarantines, rate-limit trips) scrolling
  above it as colored event lines.

Usage:
  python tools/obs_top.py --url http://127.0.0.1:PORT [--interval 1.0]
  python tools/obs_top.py --url ... --once
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

REPO_IMPORT_HINT = "advanced_scrapper_tpu"  # run from the repo root

#: always-on counters whose increments are worth a scrolling event line
WATCHED_EVENTS = (
    "astpu_fault_injected_total",
    "astpu_quarantine_total",
    "astpu_rate_limit_trips_total",
    "astpu_lease_urls_requeued_total",
)


def fetch_status(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/status", timeout=timeout) as r:
        return json.loads(r.read())


def fetch_profile(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url.rstrip("/") + "/profile", timeout=timeout) as r:
        return r.read().decode("utf-8", errors="replace")


def parse_profile(text: str) -> tuple[list[tuple[str, int]], list[str]]:
    """Folded-stack text → ``(stacks sorted hottest-first, header
    comments)``; malformed lines are skipped (the format is
    whitespace-split with a trailing count)."""
    stacks: list[tuple[str, int]] = []
    headers: list[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            headers.append(line)
            continue
        stack, _sep, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            stacks.append((stack, int(count)))
        except ValueError:
            continue
    stacks.sort(key=lambda kv: (-kv[1], kv[0]))
    return stacks, headers


def render_prof_frame(text: str, top: int = 20) -> list[str]:
    """The ``--prof`` frame: hottest stacks by sample share, leaf-first
    (the leaf names the hot code; the compressed root path gives the
    tower it lives in)."""
    stacks, headers = parse_profile(text)
    lines = list(headers)
    total = sum(c for _s, c in stacks)
    if not stacks:
        lines.append("(no samples — is ASTPU_PROFILE set on the target?)")
        return lines
    lines.append(f"{'samples':>8}  {'share':>6}  hottest stacks (leaf ← root)")
    for stack, count in stacks[:top]:
        frames = stack.split(";")
        leaf = frames[-1]
        root_path = "←".join(frames[:-1][-3:])  # the 3 frames above the leaf
        lines.append(
            f"{count:>8}  {count / total:>6.1%}  {leaf}"
            + (f"  [{root_path}]" if root_path else "")
        )
    if len(stacks) > top:
        rest = sum(c for _s, c in stacks[top:])
        lines.append(
            f"{rest:>8}  {rest / total:>6.1%}  ({len(stacks) - top} more stacks)"
        )
    return lines


def prof_summary_line(text: str) -> str:
    stacks, _headers = parse_profile(text)
    total = sum(c for _s, c in stacks)
    if not stacks:
        return "prof: no samples yet"
    leaf = stacks[0][0].split(";")[-1]
    return (
        f"prof: {total} samples over {len(stacks)} stacks | "
        f"hottest {leaf} {stacks[0][1] / total:.0%}"
    )


def _series_key(m: dict) -> str:
    labels = m.get("labels") or {}
    if not labels:
        return m["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{m['name']}{{{inner}}}"


def _index(status: dict) -> dict[str, dict]:
    return {_series_key(m): m for m in status.get("metrics", [])}


def render_frame(status: dict, prev: dict | None = None, dt: float = 0.0) -> list[str]:
    """Full-frame snapshot: stage table, then gauges, then counters.

    ``prev``/``dt`` (the previous snapshot and the seconds between them)
    add a rate column to histograms and counters; omitted for --once.
    """
    lines: list[str] = []
    idx = _index(status)
    pidx = _index(prev) if prev else {}
    ts = status.get("ts")
    head = f"obs_top @ {time.strftime('%H:%M:%S', time.localtime(ts))}"
    if "pid" in status:
        head += f"  pid={status['pid']}"
    lines.append(head)

    stages = [
        m for m in status.get("metrics", [])
        if m["name"] == "astpu_stage_seconds"
    ]
    if stages:
        lines.append("")
        lines.append(
            f"  {'stage':<14} {'count':>10} {'total_s':>10} "
            f"{'p50_ms':>9} {'p95_ms':>9} {'p99_ms':>9} {'rate/s':>9}"
        )
        for m in sorted(stages, key=lambda m: m["labels"].get("stage", "")):
            key = _series_key(m)
            rate = ""
            if key in pidx and dt > 0:
                rate = f"{(m['count'] - pidx[key].get('count', 0)) / dt:.1f}"
            lines.append(
                f"  {m['labels'].get('stage', '?'):<14} {m['count']:>10} "
                f"{m['sum']:>10.2f} {m.get('p50_ms', 0):>9.2f} "
                f"{m.get('p95_ms', 0):>9.2f} {m.get('p99_ms', 0):>9.2f} "
                f"{rate:>9}"
            )

    hists = [
        m for m in status.get("metrics", [])
        if m["kind"] == "histogram" and m["name"] != "astpu_stage_seconds"
    ]
    for m in hists:
        lines.append(
            f"  {_series_key(m):<44} n={m['count']} "
            f"p50={m.get('p50_ms', 0):.2f}ms p95={m.get('p95_ms', 0):.2f}ms"
        )

    gauges = [m for m in status.get("metrics", []) if m["kind"] == "gauge"]
    if gauges:
        lines.append("")
        lines.append("  gauges:")
        for m in sorted(gauges, key=_series_key):
            lines.append(f"    {_series_key(m):<48} {m['value']:.6g}")

    counters = [m for m in status.get("metrics", []) if m["kind"] == "counter"]
    if counters:
        lines.append("")
        lines.append("  counters:")
        for m in sorted(counters, key=_series_key):
            key = _series_key(m)
            rate = ""
            if key in pidx and dt > 0:
                rate = f"  (+{(m['value'] - pidx[key].get('value', 0)) / dt:.1f}/s)"
            lines.append(f"    {key:<48} {m['value']:.6g}{rate}")

    for section in ("lease", "control"):
        if section in status:
            lines.append("")
            lines.append(f"  {section}: {json.dumps(status[section])}")
    return lines


def render_graph_frame(
    status: dict, prev: dict | None = None, dt: float = 0.0
) -> list[str]:
    """The stage-graph view (``--graph``): every live runtime graph with
    its edges (depth/capacity, items in/out, put/get stall seconds) and
    stages (item throughput, busy seconds) — the scheduler's own gauges
    (``astpu_edge_*``, ``astpu_stage_*``), grouped by ``graph``/``g``
    instance labels.  ``prev``/``dt`` add rate columns in live mode."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}

    def rate(key: str, field: str = "value") -> str:
        if key in pidx and dt > 0:
            m, p = idx[key], pidx[key]
            return f" (+{(m.get(field, 0) - p.get(field, 0)) / dt:.1f}/s)"
        return ""

    # grouped by graph name only: counters are (graph, edge)-keyed while
    # gauges additionally carry a per-instance ``g`` label — instance
    # gauges of the same (graph, edge) are SUMMED (depth) / maxed (cap)
    graphs: dict[str, dict] = {}
    for m in status.get("metrics", []):
        name = m["name"]
        if not (name.startswith("astpu_edge_") or name.startswith("astpu_stage_items") or name.startswith("astpu_stage_busy")):
            continue
        labels = m.get("labels") or {}
        if "graph" not in labels:
            continue
        slot = graphs.setdefault(labels["graph"], {"edges": {}, "stages": {}})
        if name.startswith("astpu_edge_"):
            ekey = labels.get("edge", "?")
            slot["edges"].setdefault(ekey, {}).setdefault(
                (name, labels.get("dir") or labels.get("side") or ""), []
            ).append(m)
        else:
            slot["stages"].setdefault(labels.get("stage", "?"), {})[name] = m
    lines: list[str] = []
    if not graphs:
        return ["  (no stage-graph series — is a runtime graph live?)"]
    for gname in sorted(graphs):
        slot = graphs[gname]
        lines.append(f"  graph {gname}:")
        for ename in sorted(slot["edges"]):
            em = slot["edges"][ename]

            def val(metric: str, sub: str = "", agg=sum) -> float:
                ms = em.get((metric, sub))
                return agg(m["value"] for m in ms) if ms else 0.0

            depth = val("astpu_edge_depth")
            cap = val("astpu_edge_capacity", agg=max)
            cap_s = f"{cap:.0f}" if cap else "∞"
            in_ms = em.get(("astpu_edge_items_total", "in"))
            in_key = _series_key(in_ms[0]) if in_ms else ""
            lines.append(
                f"    edge {ename:<12} depth {depth:.0f}/{cap_s:<5} "
                f"in {val('astpu_edge_items_total', 'in'):.0f}"
                f"{rate(in_key)} "
                f"out {val('astpu_edge_items_total', 'out'):.0f}  "
                f"stall put {val('astpu_edge_stall_seconds_total', 'put'):.2f}s "
                f"get {val('astpu_edge_stall_seconds_total', 'get'):.2f}s"
            )
        for sname in sorted(slot["stages"]):
            sm = slot["stages"][sname]
            items = sm.get("astpu_stage_items_total")
            busy = sm.get("astpu_stage_busy_seconds_total")
            ikey = _series_key(items) if items else ""
            lines.append(
                f"    stage {sname:<11} items "
                f"{items['value'] if items else 0:.0f}{rate(ikey)}  "
                f"busy {busy['value'] if busy else 0:.2f}s"
            )
    return lines


def render_fleet_frame(status: dict) -> list[str]:
    """The fleet view (``--fleet``): point --url at a running collector
    (``tools/obs_fleet.py`` / ``obs.collector.FleetCollector.serve``) and
    get the per-process breakdown — endpoint health + staleness, each
    instance's headline series, harvested crash sidecars, and the SLO
    verdict series if an engine is feeding the merge."""
    lines: list[str] = []
    eps = status.get("endpoints")
    if eps is None:
        return ["  (no collector fields — is --url a FleetCollector?)"]
    for ep in eps:
        mark = "up" if ep.get("ok") else ("STALE" if ep.get("stale") else "down")
        age = f" age={ep['age_s']:.1f}s" if ep.get("age_s") is not None else ""
        err = f"  ({ep['error']})" if ep.get("error") else ""
        lines.append(
            f"  {ep['name']:<22} {mark:<5} series={ep.get('series', 0)}{age}{err}"
        )
    dead = status.get("dead_shards") or []
    if dead:
        lines.append(f"  dead shards (harvested dumps): {dead}")
    for sc in status.get("sidecars", []):
        lines.append(
            f"  sidecar {sc.get('name')}: pid={sc.get('pid')} "
            f"dumps={sc.get('dumps')} shards={sc.get('shards')}"
        )
    # per-instance headline counters (rate column in live mode)
    by_inst: dict[str, list] = {}
    for m in status.get("metrics", []):
        inst = (m.get("labels") or {}).get("instance")
        if inst and m["name"] in (
            "astpu_rpc_server_calls_total",
            "astpu_dedup_docs_total",
            "astpu_feed_docs_total",
            "astpu_lease_results_total",
        ):
            by_inst.setdefault(inst, []).append(m)
    for inst in sorted(by_inst):
        parts = [
            f"{m['name'].replace('astpu_', '')}={m['value']:.0f}"
            for m in by_inst[inst]
        ]
        lines.append(f"    {inst:<20} {'  '.join(parts)}")
    slo = [
        m for m in status.get("metrics", []) if m["name"] == "astpu_slo_compliant"
    ]
    if slo:
        lines.append("  slo:")
        for m in sorted(slo, key=_series_key):
            obj = (m.get("labels") or {}).get("objective", "?")
            burn = {
                (x.get("labels") or {}).get("window"): x["value"]
                for x in status.get("metrics", [])
                if x["name"] == "astpu_slo_burn_rate"
                and (x.get("labels") or {}).get("objective") == obj
            }
            v = m["value"]
            state = "NO-DATA" if v < 0 else ("OK " if v else "VIOLATED")
            lines.append(
                f"    {obj:<24} {state} burn fast={burn.get('fast', 0):.2f} "
                f"slow={burn.get('slow', 0):.2f}"
            )
    return lines


def render_quality_frame(
    status: dict, prev: dict | None = None, dt: float = 0.0
) -> list[str]:
    """The quality view (``--quality``): decision-mix rates from the
    always-on ``astpu_decision_total{tier,verdict}`` counters, canary
    ground-truth SLIs, and the canary SLO compliance verdicts.  Works
    against a single process endpoint or a collector merge."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}
    lines: list[str] = []

    decisions = [
        m for m in status.get("metrics", []) if m["name"] == "astpu_decision_total"
    ]
    lines.append("  decision mix (tier × verdict):")
    if not decisions:
        lines.append("    (no verdicts yet — has a dedup pass run?)")
    else:
        total = sum(m["value"] for m in decisions)
        lines.append(
            f"    {'tier':<10} {'verdict':<8} {'count':>12} {'share':>7} {'rate/s':>9}"
        )
        for m in sorted(
            decisions, key=lambda m: (-m["value"], _series_key(m))
        ):
            labels = m.get("labels") or {}
            key = _series_key(m)
            rate = ""
            if key in pidx and dt > 0:
                rate = f"{(m['value'] - pidx[key].get('value', 0)) / dt:.1f}"
            lines.append(
                f"    {labels.get('tier', '?'):<10} {labels.get('verdict', '?'):<8} "
                f"{m['value']:>12.0f} {m['value'] / total:>7.1%} {rate:>9}"
            )
        jerr = idx.get("astpu_decision_journal_errors_total")
        if jerr and jerr["value"]:
            lines.append(
                f"    journal write errors: {jerr['value']:.0f} (rows dropped whole)"
            )

    lines.append("")
    lines.append("  canary (ground-truth prober):")
    recall = idx.get("astpu_canary_recall")
    precision = idx.get("astpu_canary_precision")
    rounds = idx.get("astpu_canary_rounds_total")
    if recall is None and rounds is None:
        lines.append("    (no canary rounds yet — is a CanaryProber scheduled?)")
    else:
        lat = next(
            (
                m for m in status.get("metrics", [])
                if m["name"] == "astpu_canary_latency_seconds"
            ),
            None,
        )
        parts = []
        if recall is not None:
            parts.append(f"recall {recall['value']:.3f}")
        if precision is not None:
            parts.append(f"precision {precision['value']:.3f}")
        if rounds is not None:
            parts.append(f"rounds {rounds['value']:.0f}")
        wiped = idx.get("astpu_canary_postings_wiped_total")
        if wiped is not None:
            parts.append(f"postings wiped {wiped['value']:.0f}")
        lines.append("    " + "  ".join(parts))
        if lat:
            lines.append(
                f"    round latency: n={lat['count']} "
                f"p50={lat.get('p50_ms', 0):.1f}ms p95={lat.get('p95_ms', 0):.1f}ms"
            )

    slo = [
        m for m in status.get("metrics", [])
        if m["name"] == "astpu_slo_compliant"
        and (m.get("labels") or {}).get("objective", "").startswith("canary_")
    ]
    if slo:
        lines.append("")
        lines.append("  canary slo:")
        for m in sorted(slo, key=_series_key):
            obj = (m.get("labels") or {}).get("objective", "?")
            v = m["value"]
            state = "NO-DATA" if v < 0 else ("OK " if v else "VIOLATED")
            val = idx.get(f"astpu_slo_value{{objective={obj}}}")
            vs = f" value={val['value']:.3f}" if val else ""
            lines.append(f"    {obj:<24} {state}{vs}")
    return lines


def _tenant_ids(status: dict) -> list[str]:
    ids = set()
    for m in status.get("metrics", []):
        if m["name"].startswith("astpu_tenant_"):
            tid = (m.get("labels") or {}).get("tenant")
            if tid:
                ids.add(tid)
    return sorted(ids)


def render_tenants_frame(
    status: dict, prev: dict | None = None, dt: float = 0.0
) -> list[str]:
    """The multi-tenant front-door view (``--tenants``): per-tenant
    admit/reject/shed rates from the gateway's ``astpu_tenant_*``
    ledger, key-space posting counts, verb p99s and the per-tenant SLO
    error-budget burn.  Point ``--url`` at a gateway's metrics sidecar
    (or a collector merge)."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}
    lines: list[str] = []
    tenants = _tenant_ids(status)
    lines.append("  tenants (front-door gateway):")
    if not tenants:
        lines.append("    (no astpu_tenant_* series — is a gateway serving?)")
        return lines

    def rate(key: str, value: float) -> str:
        if key in pidx and dt > 0:
            return f"{(value - pidx[key].get('value', 0)) / dt:.1f}"
        return ""

    lines.append(
        f"    {'tenant':<12} {'verb':<13} {'outcome':<9} {'count':>10} "
        f"{'rate/s':>8}"
    )
    for m in sorted(
        (
            m for m in status.get("metrics", [])
            if m["name"] == "astpu_tenant_requests_total"
        ),
        key=_series_key,
    ):
        labels = m.get("labels") or {}
        lines.append(
            f"    {labels.get('tenant', '?'):<12} "
            f"{labels.get('verb', '?'):<13} "
            f"{labels.get('outcome', '?'):<9} {m['value']:>10.0f} "
            f"{rate(_series_key(m), m['value']):>8}"
        )
    rejects = [
        m for m in status.get("metrics", [])
        if m["name"] == "astpu_tenant_rejected_total" and m["value"]
    ]
    if rejects:
        lines.append("")
        lines.append("  quota rejects (answered RpcOverloaded + retry-after):")
        for m in sorted(rejects, key=_series_key):
            labels = m.get("labels") or {}
            lines.append(
                f"    {labels.get('tenant', '?'):<12} "
                f"{labels.get('reason', '?'):<10} {m['value']:>10.0f} "
                f"{rate(_series_key(m), m['value']):>8}"
            )

    lines.append("")
    lines.append(
        f"    {'tenant':<12} {'postings':>10} {'inflight':>9} "
        f"{'pressure':>9} {'p99_ms':>8} {'burn':>6}"
    )
    for tid in tenants:
        postings = next(
            (
                m["value"] for m in status.get("metrics", [])
                if m["name"] == "astpu_tenant_postings"
                and (m.get("labels") or {}).get("tenant") == tid
            ),
            None,
        )
        inflight = idx.get(f"astpu_admission_inflight{{gate=tenant:{tid}}}")
        pressure = idx.get(f"astpu_admission_pressure{{gate=tenant:{tid}}}")
        p99 = max(
            (
                m.get("p99_ms", 0.0) for m in status.get("metrics", [])
                if m["name"] == "astpu_tenant_seconds"
                and (m.get("labels") or {}).get("tenant") == tid
            ),
            default=0.0,
        )
        burn = max(
            (
                m["value"] for m in status.get("metrics", [])
                if m["name"] == "astpu_slo_burn_rate"
                and (m.get("labels") or {})
                .get("objective", "")
                .startswith(f"tenant_{tid}_")
                and (m.get("labels") or {}).get("window") == "fast"
            ),
            default=0.0,
        )
        post_s = "?" if postings is None else f"{postings:.0f}"
        infl_s = "?" if inflight is None else f"{inflight['value']:.0f}"
        pres_s = "?" if pressure is None else f"{pressure['value']:.2f}"
        lines.append(
            f"    {tid:<12} {post_s:>10} {infl_s:>9} {pres_s:>9} "
            f"{p99:>8.1f} {burn:>6.2f}"
        )

    bad = [
        (m.get("labels") or {}).get("objective", "?")
        for m in status.get("metrics", [])
        if m["name"] == "astpu_slo_compliant"
        and (m.get("labels") or {}).get("objective", "").startswith("tenant_")
        and m["value"] == 0
    ]
    if bad:
        lines.append("")
        lines.append(f"  tenant slo VIOLATED: {', '.join(sorted(bad))}")
    return lines


def tenants_summary_line(status: dict, prev: dict | None, dt: float) -> str:
    """Sticky one-liner for live ``--tenants`` mode: per-tenant ok/rej
    rates (hottest first) and any violated tenant objective."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}
    per: dict[str, dict[str, float]] = {}
    for key, m in idx.items():
        if m["name"] != "astpu_tenant_requests_total":
            continue
        labels = m.get("labels") or {}
        tid = labels.get("tenant", "?")
        outcome = labels.get("outcome", "?")
        d = (
            (m["value"] - pidx[key].get("value", 0)) / dt
            if key in pidx and dt > 0
            else 0.0
        )
        per.setdefault(tid, {})
        per[tid][outcome] = per[tid].get(outcome, 0.0) + d
    if not per:
        return "(no tenant series yet)"
    ranked = sorted(
        per.items(), key=lambda kv: -sum(kv[1].values())
    )
    parts = [
        f"{tid} ok {o.get('ok', 0):.0f}/s rej {o.get('rejected', 0):.0f}/s"
        for tid, o in ranked[:4]
    ]
    bad = [
        (m.get("labels") or {}).get("objective", "?")
        for m in status.get("metrics", [])
        if m["name"] == "astpu_slo_compliant"
        and (m.get("labels") or {}).get("objective", "").startswith("tenant_")
        and m["value"] == 0
    ]
    if bad:
        parts.append(f"slo violated: {','.join(sorted(bad))}")
    return " | ".join(parts)


def quality_summary_line(status: dict, prev: dict | None, dt: float) -> str:
    """Sticky one-liner for live ``--quality`` mode: canary SLIs, the
    hottest decision tiers by rate, and any violated canary objective."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}
    parts = []
    recall = idx.get("astpu_canary_recall")
    precision = idx.get("astpu_canary_precision")
    if recall is not None or precision is not None:
        r = f"{recall['value']:.2f}" if recall else "?"
        p = f"{precision['value']:.2f}" if precision else "?"
        parts.append(f"canary R={r} P={p}")
    rates = []
    for key, m in idx.items():
        if m["name"] != "astpu_decision_total" or key not in pidx or dt <= 0:
            continue
        d = (m["value"] - pidx[key].get("value", 0)) / dt
        if d > 0:
            labels = m.get("labels") or {}
            rates.append((d, f"{labels.get('tier')}:{labels.get('verdict')}"))
    if rates:
        rates.sort(reverse=True)
        parts.append(
            "mix " + " ".join(f"{k} {d:.0f}/s" for d, k in rates[:3])
        )
    bad = [
        (m.get("labels") or {}).get("objective", "?")
        for m in status.get("metrics", [])
        if m["name"] == "astpu_slo_compliant"
        and (m.get("labels") or {}).get("objective", "").startswith("canary_")
        and m["value"] == 0
    ]
    if bad:
        parts.append(f"slo violated: {','.join(sorted(bad))}")
    return " | ".join(parts) if parts else "(no quality series yet)"


def fleet_summary_line(status: dict, prev: dict | None, dt: float) -> str:
    """Sticky one-liner for live ``--fleet`` mode: up/total endpoints,
    dead shards, violated objectives."""
    eps = status.get("endpoints")
    if eps is None:
        return "(not a collector endpoint)"
    up = sum(1 for e in eps if e.get("ok"))
    parts = [f"fleet {up}/{len(eps)} up"]
    dead = status.get("dead_shards") or []
    if dead:
        parts.append(f"dead: {','.join(dead)}")
    bad = [
        (m.get("labels") or {}).get("objective", "?")
        for m in status.get("metrics", [])
        if m["name"] == "astpu_slo_compliant" and not m["value"]
    ]
    if bad:
        parts.append(f"slo violated: {','.join(sorted(bad))}")
    return " | ".join(parts)


def graph_summary_line(status: dict, prev: dict | None, dt: float) -> str:
    """Sticky one-liner for live ``--graph`` mode: total edge depth and
    the hottest stall side per graph."""
    idx = _index(status)
    per_graph: dict[str, float] = {}
    stall: dict[str, float] = {}
    for k, m in idx.items():
        labels = m.get("labels") or {}
        if m["name"] == "astpu_edge_depth" and "graph" in labels:
            per_graph[labels["graph"]] = (
                per_graph.get(labels["graph"], 0.0) + m["value"]
            )
        if m["name"] == "astpu_edge_stall_seconds_total" and "graph" in labels:
            stall[labels["graph"]] = max(
                stall.get(labels["graph"], 0.0), m["value"]
            )
    if not per_graph:
        return "(no stage-graph series)"
    parts = [
        f"{g}: depth {d:.0f} stall≤{stall.get(g, 0):.1f}s"
        for g, d in sorted(per_graph.items())
    ]
    return " | ".join(parts)


def summary_line(status: dict, prev: dict | None, dt: float) -> str:
    """The sticky one-liner: per-stage rates + queue depth + fleet health."""
    idx = _index(status)
    pidx = _index(prev) if prev else {}

    def rate_of(name: str, labels: str = "") -> float:
        key = name + labels
        m, p = idx.get(key), pidx.get(key)
        if m is None or p is None or dt <= 0:
            return 0.0
        field = "count" if m.get("kind") == "histogram" else "value"
        return (m.get(field, 0) - p.get(field, 0)) / dt

    parts = []
    for stage in ("encode", "h2d", "kernel", "resolve"):
        r = rate_of("astpu_stage_seconds", f"{{stage={stage}}}")
        if r:
            parts.append(f"{stage} {r:.0f}/s")
    depth = sum(
        m["value"] for k, m in idx.items() if k.startswith("astpu_feed_queue_depth")
    )
    if depth:
        parts.append(f"queue {depth:.0f}")
    lease = status.get("lease")
    if lease:
        parts.append(
            f"lease pending={lease.get('pending')} "
            f"clients={len(lease.get('clients', {}))}"
        )
    docs = rate_of("astpu_feed_docs_total")
    if docs:
        parts.append(f"feed {docs:.0f} docs/s")
    return " | ".join(parts) if parts else "(no activity yet)"


def watch_events(status: dict, prev: dict | None) -> list[tuple[str, bool]]:
    """``(message, is_bad)`` for every watched counter that moved."""
    if prev is None:
        return []
    idx, pidx = _index(status), _index(prev)
    out = []
    for key, m in idx.items():
        if m.get("kind") != "counter" or m["name"] not in WATCHED_EVENTS:
            continue
        delta = m["value"] - pidx.get(key, {}).get("value", 0)
        if delta > 0:
            out.append((f"{key} +{delta:.0f}", True))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True, help="base url, e.g. http://127.0.0.1:9100")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true", help="one frame, then exit")
    ap.add_argument(
        "--graph",
        action="store_true",
        help="stage-graph view: live edge depths/stall times and per-stage "
        "throughput from the runtime's own gauges",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="fleet view: point --url at a metrics collector "
        "(tools/obs_fleet.py) for per-process health, harvested crash "
        "sidecars and SLO verdicts",
    )
    ap.add_argument(
        "--prof",
        action="store_true",
        help="profiler view: render GET /profile's hottest folded stacks "
        "(a process under ASTPU_PROFILE, or a collector's merged view)",
    )
    ap.add_argument(
        "--prof-top", type=int, default=20,
        help="stacks shown in the --prof frame",
    )
    ap.add_argument(
        "--quality",
        action="store_true",
        help="quality view: decision-mix rates (astpu_decision_total), "
        "canary ground-truth SLIs and the canary SLO verdicts",
    )
    ap.add_argument(
        "--tenants",
        action="store_true",
        help="multi-tenant view: per-tenant admit/reject rates, quota "
        "refusals by reason, key-space posting counts, p99 and SLO "
        "error-budget burn (point --url at a gateway's metrics sidecar)",
    )
    ap.add_argument(
        "--frames", type=int, default=0, help="stop after N polls (0 = forever)"
    )
    args = ap.parse_args(argv)

    if args.once:
        if args.prof:
            try:
                text = fetch_profile(args.url)
            except OSError as e:
                print(f"obs_top: cannot reach {args.url}: {e}", file=sys.stderr)
                return 1
            head = f"obs_top --prof @ {time.strftime('%H:%M:%S')}"
            print("\n".join([head] + render_prof_frame(text, args.prof_top)))
            return 0
        try:
            status = fetch_status(args.url)
        except OSError as e:
            print(f"obs_top: cannot reach {args.url}: {e}", file=sys.stderr)
            return 1
        if args.fleet:
            lines = render_fleet_frame(status)
        elif args.graph:
            lines = render_graph_frame(status)
        elif args.quality:
            lines = render_quality_frame(status)
        elif args.tenants:
            lines = render_tenants_frame(status)
        else:
            lines = render_frame(status)
        if args.graph or args.fleet or args.quality or args.tenants:
            mode = (
                "--fleet" if args.fleet
                else "--graph" if args.graph
                else "--quality" if args.quality
                else "--tenants"
            )
            head = f"obs_top {mode} @ {time.strftime('%H:%M:%S', time.localtime(status.get('ts')))}"
            lines = [head] + lines
        print("\n".join(lines))
        return 0

    if args.prof:
        # live profiler mode: the sticky line tracks total samples + the
        # hottest leaf; ^C exits like the other live views
        from advanced_scrapper_tpu.obs.console import ConsoleMux, red

        mux = ConsoleMux().start()
        n = 0
        try:
            while True:
                try:
                    mux.stats(prof_summary_line(fetch_profile(args.url)))
                except OSError as e:
                    mux.stats(red(f"unreachable: {e}"))
                n += 1
                if args.frames and n >= args.frames:
                    return 0
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        finally:
            mux.stop()
            print()

    from advanced_scrapper_tpu.obs.console import ConsoleMux, green, red

    mux = ConsoleMux().start()
    prev = None
    t_prev = 0.0
    n = 0
    try:
        while True:
            try:
                status = fetch_status(args.url)
            except OSError as e:
                mux.stats(red(f"unreachable: {e}"))
                time.sleep(args.interval)
                continue
            now = time.monotonic()
            dt = now - t_prev if prev is not None else 0.0
            for msg, bad in watch_events(status, prev):
                mux.event(red(msg) if bad else green(msg))
            if args.fleet:
                sticky = fleet_summary_line(status, prev, dt)
            elif args.graph:
                sticky = graph_summary_line(status, prev, dt)
            elif args.quality:
                sticky = quality_summary_line(status, prev, dt)
            elif args.tenants:
                sticky = tenants_summary_line(status, prev, dt)
            else:
                sticky = summary_line(status, prev, dt)
            mux.stats(sticky)
            prev, t_prev = status, now
            n += 1
            if args.frames and n >= args.frames:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        mux.stop()
        print()


if __name__ == "__main__":
    raise SystemExit(main())
