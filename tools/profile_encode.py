"""Measure blockwise encode throughput: native C vs the Python oracle.

The host half of the ragged regime is block assembly (ragged UTF-8 →
fixed uint8[N, block] rows).  SURVEY §7 named the C++ batcher the hard
part because the host must sustain the north-star 50k articles/s of
block assembly or the device never sees enough work.  This driver
measures exactly that, on the bench's ragged corpus distribution
(mixed 300 B news briefs / 3 KB articles / 40 KB long reads — see
bench.py), best-of-N on both paths.

Run: PYTHONPATH=/root/repo python tools/profile_encode.py
"""
from __future__ import annotations

import json
import time

import numpy as np


def ragged_corpus(n: int, seed: int = 7) -> list[bytes]:
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        r = rng.rand()
        if r < 0.70:
            size = rng.randint(200, 600)      # news brief
        elif r < 0.95:
            size = rng.randint(2000, 5000)    # standard article
        else:
            size = rng.randint(20000, 60000)  # long read
        out.append(rng.randint(32, 127, size=size, dtype=np.uint8).tobytes())
    return out


def bestof(fn, n=5):
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    from advanced_scrapper_tpu.core import tokenizer
    from advanced_scrapper_tpu.cpu.hostbatch import encode_blocks_native

    block, overlap = 1024, 4
    docs = ragged_corpus(8192)
    total_bytes = sum(len(d) for d in docs)

    native = encode_blocks_native(docs, block, overlap)
    assert native is not None, "native hostbatch lib missing"

    # Python oracle on a subsample (it is the slow path by design);
    # measured by bypassing the native hook
    sub = docs[:512]
    import advanced_scrapper_tpu.cpu.hostbatch as hb

    t_native = bestof(lambda: encode_blocks_native(docs, block, overlap))

    real_native = hb.encode_blocks_native
    hb.encode_blocks_native = lambda *a, **k: None
    try:
        t_py_sub = bestof(
            lambda: tokenizer.encode_blocks(sub, block, overlap=overlap), n=3
        )
    finally:
        hb.encode_blocks_native = real_native

    arts_native = len(docs) / t_native
    arts_py = len(sub) / t_py_sub
    blocks = native[0].shape[0]
    print(json.dumps({
        "corpus_docs": len(docs),
        "corpus_mb": round(total_bytes / 1e6, 1),
        "blocks": int(blocks),
        "block_len": block,
        "native_s": round(t_native, 4),
        "native_articles_per_s": round(arts_native),
        "native_mb_per_s": round(total_bytes / t_native / 1e6, 1),
        "python_articles_per_s": round(arts_py),
        "speedup": round(arts_native / arts_py, 1),
        "vs_50k_target": round(arts_native / 50000, 1),
    }))


if __name__ == "__main__":
    main()
