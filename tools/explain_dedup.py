#!/usr/bin/env python
"""Explain-query CLI over the decision journal: why is doc X a dup of Y?

``obs/decisions.py`` journals every dedup verdict with the tier that
settled it, the attributed doc and the winning band key.  This tool
joins those records against the persistent index so an operator can
resolve one verdict's FULL decision path::

    python tools/explain_dedup.py --journal decisions.jsonl --doc 42
    python tools/explain_dedup.py --journal decisions.jsonl \
        --name https://ex.ample/page --index /data/idx/bands
    python tools/explain_dedup.py --journal decisions.jsonl --list
    python tools/explain_dedup.py --journal decisions.jsonl --mix

With ``--index DIR`` the explanation is *verified*, not just replayed:
the record's winning band key is re-probed against the live postings
(read-only open — safe beside a writer) and the answer is compared with
the journaled attribution; both docs' urls resolve through the docmap
sidecar (``lookup_names``).  Without an index the tool prints the
journal's own record (still the full tier/band/attribution path).

``--format json`` emits one JSON object per selected record for
scripting; ``--mix`` prints the journal's tier×verdict histogram (the
offline twin of the live ``astpu_decision_total`` counters).

Deliberately jax-free: explain queries must run on a box whose tunnel
is dead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from advanced_scrapper_tpu.obs.decisions import DecisionJournal  # noqa: E402


def load_records(path: str) -> list[dict]:
    recs = DecisionJournal.read(path)
    if not recs:
        print(f"explain_dedup: no records in {path!r}", file=sys.stderr)
    return recs


def select(recs: list[dict], args) -> list[dict]:
    out = recs
    if args.doc is not None:
        out = [r for r in out if r.get("doc") == args.doc]
    if args.name:
        out = [r for r in out if r.get("name") == args.name]
    if args.tier:
        out = [r for r in out if r.get("tier") == args.tier]
    if args.verdict:
        out = [r for r in out if r.get("verdict") == args.verdict]
    return out


def open_index(directory: str):
    from advanced_scrapper_tpu.index import PersistentIndex

    return PersistentIndex(directory, read_only=True)


def verify_against_index(rec: dict, index) -> dict:
    """Join one journal record against live postings: re-probe the
    winning band key and compare with the journaled attribution."""
    import numpy as np

    out: dict = {}
    band_key = rec.get("band_key")
    attr = rec.get("attr", -1)
    if band_key is not None:
        probed = int(
            np.asarray(
                index.probe_batch(np.asarray([band_key], np.uint64))
            )[0]
        )
        out["probed_doc"] = probed
        out["consistent"] = bool(probed == attr) if attr >= 0 else None
    ids = [d for d in (rec.get("doc"), attr) if isinstance(d, int) and d >= 0]
    if ids:
        out["names"] = {
            str(k): v for k, v in index.lookup_names(ids).items()
        }
    return out


TIER_GLOSS = {
    "exact": "byte/url-identity stage (memcmp-confirmed first seen)",
    "index": "persistent/bloom stream-index posting hit",
    "band": "LSH band collision settled by the signature estimator",
    "rerank": "device bottom-sketch settle (precision tier)",
    "margin": "host exact-Jaccard re-settle of the margin band",
    "reprobe": "borderline ANN re-probe over index postings",
}


def render(rec: dict, joined: dict | None) -> str:
    tier = rec.get("tier", "?")
    verdict = rec.get("verdict", "?")
    doc = rec.get("doc")
    attr = rec.get("attr", -1)
    lines = [f"doc {doc}" + (f" ({rec['name']})" if rec.get("name") else "")]
    lines.append(f"  verdict : {verdict}")
    lines.append(
        f"  tier    : {tier} — {TIER_GLOSS.get(tier, 'unknown tier')}"
    )
    if verdict == "dup":
        lines.append(f"  dup of  : {attr}")
    bk = rec.get("band_key")
    lines.append(
        f"  band key: {bk if bk is not None else '(transitive/none)'}"
    )
    if rec.get("regime"):
        lines.append(f"  regime  : {rec['regime']}")
    if rec.get("seq") is not None:
        lines.append(f"  journal : seq={rec['seq']} ts={rec.get('ts')}")
    if joined:
        if "probed_doc" in joined:
            mark = {True: "CONSISTENT", False: "MISMATCH", None: "n/a"}[
                joined.get("consistent")
            ]
            lines.append(
                f"  index   : band key re-probe → doc "
                f"{joined['probed_doc']} [{mark}]"
            )
        for did, nm in (joined.get("names") or {}).items():
            lines.append(f"  name    : doc {did} = {nm}")
    return "\n".join(lines)


def decision_mix(recs: list[dict]) -> dict:
    mix: dict[str, int] = {}
    for r in recs:
        k = f"{r.get('tier', '?')}:{r.get('verdict', '?')}"
        mix[k] = mix.get(k, 0) + 1
    return mix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="explain dedup verdicts from the decision journal"
    )
    ap.add_argument("--journal", required=True, help="decision JSONL path")
    ap.add_argument("--doc", type=int, default=None, help="doc id to explain")
    ap.add_argument("--name", default=None, help="doc name/url to explain")
    ap.add_argument("--tier", default=None, help="filter by settling tier")
    ap.add_argument("--verdict", default=None, choices=("dup", "unique"))
    ap.add_argument(
        "--index", default=None,
        help="persistent index dir: verify band keys + resolve names",
    )
    ap.add_argument("--list", action="store_true", help="list all records")
    ap.add_argument(
        "--mix", action="store_true", help="print tier×verdict histogram"
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    recs = load_records(args.journal)
    if args.mix:
        mix = decision_mix(recs)
        if args.format == "json":
            print(json.dumps(mix, sort_keys=True))
        else:
            for k in sorted(mix):
                print(f"{k:>16}: {mix[k]}")
        return 0
    if not (args.list or args.doc is not None or args.name):
        print(
            "explain_dedup: pick a selector (--doc / --name / --list / --mix)",
            file=sys.stderr,
        )
        return 2
    sel = select(recs, args)
    if not sel:
        print("explain_dedup: no matching records", file=sys.stderr)
        return 1
    index = open_index(args.index) if args.index else None
    try:
        for rec in sel:
            joined = verify_against_index(rec, index) if index else None
            if args.format == "json":
                out = dict(rec)
                if joined:
                    out["index_join"] = joined
                print(json.dumps(out, sort_keys=True))
            else:
                print(render(rec, joined))
                print()
    finally:
        if index is not None:
            index.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
