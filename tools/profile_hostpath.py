"""One-command per-stage profile of the ragged host path.

Runs bench.py's ragged corpus through the NearDupEngine twice (cold shapes,
then warm) and prints the ``obs/stages`` attribution — encode (host
blockwise split), h2d (device_put), kernel (signature dispatch + sync
waits), resolve (LSH resolution + rep readback) — plus the articles/s the
warm pass achieves.  CPU-safe (runs on whatever backend jax resolves; use
``env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu`` to force the CPU mesh)
and small enough for CI smoke (tests/test_tools.py), so the stage
decomposition can't rot as the path evolves.

``--device`` adds the dispatch-executor view: a per-tile timeline of the
warm corpus (width, rows, packed H2D bytes, put and dispatch
milliseconds — ``NearDupEngine.dispatch_probe``) plus the always-on
device-traffic counter deltas (puts / dispatches / H2D bytes,
``obs/stages.py``), so the 1-put/1-dispatch-per-tile contract is
inspectable per corpus, not just asserted in tests.  The same flag also
runs the MATCHER tile plane (``bench._matcher_workload`` through
``EntityIndex.dispatch_probe``) and prints its per-tile timeline and
counter deltas — the matcher half of the launch-count ledger.

Usage:
    python tools/profile_hostpath.py            # 2048 articles
    python tools/profile_hostpath.py 512        # smaller corpus
    python tools/profile_hostpath.py 512 --device   # + per-tile timelines
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main(n_articles: int = 2048, device: bool = False) -> None:
    import jax

    import bench
    from advanced_scrapper_tpu.obs import stages
    from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

    rng = np.random.RandomState(7)
    engine = NearDupEngine()
    corpus = bench._ragged_corpus(rng, n_articles)
    n_bytes = sum(len(c) for c in corpus)

    stages.reset()
    t0 = time.perf_counter()
    # cold pass rides the same async path the warm pass times, so "warm"
    # is genuinely warm (no fused-resolve compile left for pass 2)
    np.asarray(engine.dedup_reps_async(corpus))
    t_cold = time.perf_counter() - t0
    cold = stages.snapshot_ms()

    corpus2 = bench._ragged_corpus(rng, n_articles)
    tiles: list[dict] = []
    if device:
        engine.dispatch_probe = tiles.append
    dc0 = stages.device_counters()
    stages.reset()
    t0 = time.perf_counter()
    rep = engine.dedup_reps_async(corpus2)
    with stages.timed("resolve"):
        rep = np.asarray(rep)[:n_articles]
    t_warm = time.perf_counter() - t0
    warm = stages.snapshot_ms()
    engine.dispatch_probe = None
    assert rep.shape == (n_articles,)

    def fmt(d: dict) -> str:
        keys = ("encode", "h2d", "kernel", "resolve")
        return " ".join(f"{k}={d.get(k, 0.0):.1f}ms" for k in keys)

    print(
        f"hostpath ragged {n_articles} articles ({n_bytes / 1e6:.1f} MB): "
        f"cold={t_cold:.2f}s [{fmt(cold)}] "
        f"warm={t_warm:.2f}s [{fmt(warm)}] "
        f"→ {n_articles / t_warm:.0f} articles/s warm "
        f"(stage sums overlap by design; see obs/stages.py)"
    )
    if device:
        dc = stages.device_counters()
        print(
            "device view (warm corpus): "
            f"puts={int(dc['device_puts'] - dc0['device_puts'])} "
            f"dispatches="
            f"{int(dc['device_dispatches'] - dc0['device_dispatches'])} "
            f"h2d_bytes={int(dc['h2d_bytes'] - dc0['h2d_bytes'])} "
            f"tiles={len(tiles)} "
            "(packed async: 1 put + 1 dispatch per tile, +1 put "
            "[valid mask] and +1 dispatch [fused resolve epilogue] "
            "per corpus)"
        )
        for t in tiles:
            print(
                f"  tile {t['tile']:3d}  w={t['width']:5d} "
                f"rows={t['rows']:5d}  h2d={t['h2d_bytes']:9d}B "
                f"put={t['put_ms']:7.2f}ms  dispatch={t['dispatch_ms']:7.2f}ms"
            )

        # the matcher tile plane: same ledger, the screen workload
        from advanced_scrapper_tpu.pipeline.matcher import match_chunk

        index, df = bench._matcher_workload(max(64, n_articles // 8))
        match_chunk(df, index)  # warm the screen-step shapes
        m_tiles: list[dict] = []
        index.dispatch_probe = m_tiles.append
        dm0 = stages.device_counters()
        match_chunk(df, index)
        dm = stages.device_counters()
        index.dispatch_probe = None
        print(
            "matcher device view (warm chunk): "
            f"puts={int(dm['device_puts'] - dm0['device_puts'])} "
            f"dispatches="
            f"{int(dm['device_dispatches'] - dm0['device_dispatches'])} "
            f"h2d_bytes={int(dm['h2d_bytes'] - dm0['h2d_bytes'])} "
            f"tiles={len(m_tiles)} "
            "(packed: 1 put + 1 fused screen dispatch per tile, "
            "nothing else per chunk)"
        )
        for t in m_tiles:
            print(
                f"  tile {t['tile']:3d}  w={t['width']:5d} "
                f"rows={t['rows']:5d}  h2d={t['h2d_bytes']:9d}B "
                f"put={t['put_ms']:7.2f}ms  dispatch={t['dispatch_ms']:7.2f}ms"
            )


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if a != "--device"]
    main(
        *[int(a) for a in args[:1]],
        device="--device" in sys.argv[1:],
    )
