"""Sweep the borderline-edge handling knobs against the precision budget.

Fine sub-band candidacy reaches far below the coarse banding's knee;
estimator noise (σ≈0.04 at 128 perms) then verifies some true-J<0.7
pairs that datasketch's own banding never proposes — the ~3-point
precision giveback VERDICT r4 item 4 put a budget on (precision ≥
oracle − 0.01 at recall ≥ 0.95).  Two frontiers are measured on the
hardened certification corpus:

- ``fine_margin`` (estimator-only): raising the bar on fine-only edges.
  CANNOT meet the budget — the false merges and the genuine bridges that
  recover cross-estimator disagreement (5.9% of oracle pairs have
  engine-est < 0.7; the oracle is datasketch's sha1+61-bit-Mersenne
  construction, the engine's is FNV+u32-affine) ride the same agreement
  band, so every point trades one metric for the other.
- ``exact_verify_band``: confirm statistically fragile edges by EXACT
  shingle-set Jaccard (host, one-shot path).  Separates the two classes
  perfectly and meets the budget at ~130 checks per 2048 docs.

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
       python tools/sweep_fine_margin.py [n_bases]
"""
from __future__ import annotations

import dataclasses
import json
import sys

import numpy as np


def main() -> None:
    n_bases = int(sys.argv[1]) if len(sys.argv) > 1 else 256

    from advanced_scrapper_tpu.config import DedupConfig
    from advanced_scrapper_tpu.core.hashing import make_params
    from advanced_scrapper_tpu.cpu.oracle import (
        build_certification_corpus,
        measured_precision,
        measured_recall,
        oracle_near_dup_pairs,
        oracle_reps,
    )
    from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

    rng = np.random.RandomState(7)
    params = make_params()
    texts = build_certification_corpus(rng, n_bases, n_long=min(12, n_bases // 8))
    opairs = oracle_near_dup_pairs(texts, params, 0.7, fast=True)
    o_prec, _, _ = measured_precision(
        texts, oracle_reps(texts, params, 0.7, pairs=opairs), params.shingle_k, 0.7
    )
    print(f"oracle precision {o_prec:.4f} over {len(opairs)} pairs", file=sys.stderr)

    rows = []
    # estimator-only frontier (exact verification disabled), then the
    # exact-verify band frontier at margin 0 — the mechanism that ships
    configs = [
        {"fine_margin": m, "exact_verify_band": 0.0}
        for m in (0.0, 0.01, 0.02, 0.04, 0.08)
    ] + [
        {"fine_margin": 0.0, "exact_verify_band": b}
        for b in (0.70, 0.71, 0.72, 0.74)
    ]
    for overrides in configs:
        cfg = dataclasses.replace(DedupConfig(), **overrides)
        reps = NearDupEngine(cfg).dedup_reps(texts)
        recall, _ = measured_recall(texts, reps, params, 0.7, pairs=opairs)
        prec, merged, unchained = measured_precision(
            texts, reps, params.shingle_k, 0.7
        )
        rows.append(
            {
                **overrides,
                "recall": round(recall, 4),
                "precision": round(prec, 4),
                "vs_oracle_precision": round(prec - o_prec, 4),
                "merged_pairs": merged,
                "unchained": unchained,
                "meets_budget": recall >= 0.95 and prec >= o_prec - 0.01,
            }
        )
        print(json.dumps(rows[-1]), file=sys.stderr)
    print(json.dumps({"oracle_precision": round(o_prec, 4), "sweep": rows}))


if __name__ == "__main__":
    main()
