"""LSHBloom soak: measured false-drop rate + flat memory at stream scale.

VERDICT r3 item 6: the 10M-scale claims in ``utils/bloom.py`` were
extrapolated from the Bloom formula, never measured.  This soak drives
millions of synthetic band-key rows through :class:`BloomBandIndex`
(vectorised numpy — no jax, no device) and, at checkpoints, probes with
FRESH unique keys (ground truth: an exact index would keep every one), so
every positive is a measured false drop.  It reports measured vs formula
rate, fill ratio, and memory at each checkpoint.

The corpus generator draws uniform uint64 keys, so intra-run band-key
collisions (ε_key ≈ n·nb/2⁶⁴) are negligible and the measurement isolates
the filter term — the term the module docstring's math describes.

Usage:
    python tools/soak_bloom.py                 # 10M keys, default 2^24 bits
    python tools/soak_bloom.py 2000000         # 2M keys
    python tools/soak_bloom.py 10000000 29     # 10M keys, 2^29 bits/band
                                               # (the for_capacity sizing
                                               # for 10M @ row_fp 1e-3)

Prints one JSON line per checkpoint and a final summary line.
"""

from __future__ import annotations

import json
import resource
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from advanced_scrapper_tpu.utils.bloom import BloomBandIndex  # noqa: E402

BATCH = 1 << 16
PROBE = 200_000
NUM_BANDS = 16


def soak(n_keys: int, bits_log2: int, num_hashes: int = 4) -> dict:
    ix = BloomBandIndex(NUM_BANDS, bits=1 << bits_log2, num_hashes=num_hashes)
    rng = np.random.RandomState(17)
    checkpoints = sorted(
        {n_keys // 20, n_keys // 8, n_keys // 4, n_keys // 2, n_keys}
    )
    next_cp = 0
    inserted = 0
    mem0 = ix.memory_bytes
    t0 = time.perf_counter()
    out: list[dict] = []
    while inserted < n_keys:
        b = min(BATCH, n_keys - inserted)
        # uniform uint64 keys: unique with overwhelming probability, so
        # check_and_add_batch marking ANY row dup is a false drop
        keys = rng.randint(0, 2**64, size=(b, NUM_BANDS), dtype=np.uint64)
        ix.add_batch(keys)
        inserted += b
        if inserted >= checkpoints[next_cp]:
            probe = rng.randint(0, 2**64, size=(PROBE, NUM_BANDS), dtype=np.uint64)
            fp = float(ix.contains_batch(probe).mean())
            rec = {
                "inserted": inserted,
                "measured_row_fp": round(fp, 6),
                "predicted_row_fp": round(ix.predicted_row_fp(), 6),
                "fill_ratio": round(ix.fill_ratio(), 4),
                "memory_bytes": ix.memory_bytes,
                "rss_mib": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024,
                "elapsed_s": round(time.perf_counter() - t0, 1),
            }
            out.append(rec)
            print(json.dumps(rec), flush=True)
            while next_cp < len(checkpoints) and inserted >= checkpoints[next_cp]:
                next_cp += 1
    assert ix.memory_bytes == mem0, "index memory must never grow"
    summary = {
        "soak": "bloom",
        "n_keys": n_keys,
        "bits_per_band_log2": bits_log2,
        "num_hashes": num_hashes,
        "memory_flat": True,
        "memory_bytes_total": mem0,
        "checkpoints": out,
    }
    print(json.dumps(summary), flush=True)
    return summary


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    bl = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    soak(n, bl)
