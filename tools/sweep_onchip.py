"""On-chip knob sweep orchestrator — run the moment the tunnel is healthy.

VERDICT r3 item 1 wants BENCH_r04 captured on the chip with the ragged and
stream regimes swept over their tuning knobs (feed workers, put workers,
batch size).  The tunnel has repeatedly died mid-session, so this driver is
built for hostile transport: every configuration runs in its OWN subprocess
under a hard watchdog, results append to a JSONL file as they land, and a
dead config (hang or transport error) is recorded and skipped rather than
taking the sweep down.

Usage:
    python tools/sweep_onchip.py                # full sweep -> sweep_onchip.jsonl
    python tools/sweep_onchip.py --quick        # 1/4-size shapes, short list
    python tools/sweep_onchip.py --out PATH --timeout 900

Interpret: take the best stream/ragged rows, set
``ASTPU_BENCH_FEED_WORKERS`` / ``ASTPU_DEDUP_PUT_WORKERS`` /
``ASTPU_BENCH_BATCH`` accordingly, then run ``python bench.py`` for the
round record.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE_SNIPPET = (
    "import jax, json; d = jax.devices(); "
    "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
)

STREAM_SNIPPET = """
import json, os, sys, threading, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.core.hashing import make_params
from advanced_scrapper_tpu.core.mesh import build_mesh
from advanced_scrapper_tpu.cpu.hostbatch import HostBatcher
from advanced_scrapper_tpu.parallel.sharded import make_sharded_dedup, shard_batch
from advanced_scrapper_tpu.pipeline.feed import DeviceFeed

batch, block, n_batches, workers = {batch}, {block}, {n_batches}, {workers}
params = make_params()
mesh = build_mesh(len(jax.devices()), 1)
base, docs = bench._stream_corpus(batch, block)
step = make_sharded_dedup(mesh, params, backend="scan")
warm = shard_batch(base, np.full((batch,), block, np.int32), mesh)
jax.block_until_ready(step(*warm))
batcher = HostBatcher(block)
feed = DeviceFeed(batcher, batch, depth=4, workers=workers)
def produce():
    for b in range(n_batches):
        batcher.feed(docs, start_tag=b * batch, chunk=4096)
    batcher.close()
t0 = time.perf_counter()
threading.Thread(target=produce, daemon=True).start()
pending = []
for n, tok_dev, len_dev, tags in feed:
    rep, _h = step(tok_dev, len_dev)
    try:
        rep.copy_to_host_async()   # same readback overlap as bench._bench_stream
    except AttributeError:
        pass
    pending.append((rep, tags, n))
outs = [tags[np.asarray(rep)[:n]] for rep, tags, n in pending]
dt = time.perf_counter() - t0
feed.join()
total = batch * n_batches
assert sum(o.shape[0] for o in outs) == total
print(json.dumps({{"articles_per_sec": round(total / dt, 1)}}))
"""

RAGGED_SNIPPET = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

n = {n_articles}
rng = np.random.RandomState(7)
# explicit config: NearDupEngine() raw defaults ignore env knobs
engine = NearDupEngine(DedupConfig(put_workers={put_workers}))
engine.dedup_reps(bench._ragged_corpus(rng, n))      # warm all shapes
corpus = bench._ragged_corpus(rng, n)
t0 = time.perf_counter()
rep = np.asarray(engine.dedup_reps_async(corpus))[:n]
dt = time.perf_counter() - t0
print(json.dumps({{"articles_per_sec": round(n / dt, 1)}}))
"""

SHARDED_SNIPPET = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.mesh import build_mesh
from advanced_scrapper_tpu.obs import stages
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

n, dp, sp = {n_articles}, {dp}, {sp}
rng = np.random.RandomState(7)
engine = NearDupEngine(DedupConfig(put_workers={put_workers}))
# sub-count shapes (dp*sp < devices) sweep a carved sub-mesh: build_mesh
# requires len(devices) == dp*sp, so hand it exactly that many
mesh = build_mesh(dp, sp, devices=jax.devices()[: dp * sp])
engine.prewarm_sharded(mesh, n)                       # warm the shape set
engine.dedup_reps_sharded(bench._ragged_corpus(rng, n), mesh)
corpus = bench._ragged_corpus(rng, n)
ps0 = stages.sharded_device_counters()
t0 = time.perf_counter()
rep = engine.dedup_reps_sharded(corpus, mesh)
dt = time.perf_counter() - t0
ps1 = stages.sharded_device_counters()
puts = sorted(
    ps1[s]["device_puts"] - ps0.get(s, {{}}).get("device_puts", 0.0)
    for s in ps1
)
print(json.dumps({{
    "articles_per_sec": round(n / dt, 1),
    "mesh": [dp, sp],
    "tiles": engine.last_tiles,
    "per_shard_puts": [puts[0], puts[-1]],
}}))
"""


def run_config(tag: str, snippet: str, env: dict, timeout: float) -> dict:
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", snippet],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=HERE,
        )
    except subprocess.TimeoutExpired:
        return {"config": tag, "status": "timeout", "elapsed_s": round(time.time() - t0, 1)}
    rec: dict = {
        "config": tag,
        "status": "ok" if proc.returncode == 0 else "error",
        "elapsed_s": round(time.time() - t0, 1),
    }
    if proc.returncode == 0:
        try:
            rec.update(json.loads(proc.stdout.strip().splitlines()[-1]))
        except (ValueError, IndexError):
            rec["status"] = "unparseable"
            rec["stdout_tail"] = proc.stdout[-300:]
    else:
        rec["stderr_tail"] = proc.stderr[-300:]
    return rec


def parse_mesh_shape(spec: str) -> tuple[int, int]:
    """``"2x4"`` → ``(2, 4)`` — local twin of
    ``core.mesh.parse_mesh_shape`` (same DxS grammar, asserted in
    tests).  Deliberately NOT imported from the package: this parent
    process must never import jax (a dead tunnel can hang backend-
    touching imports forever; every jax-touching config runs in its own
    watchdogged subprocess)."""
    parts = spec.lower().strip().split("x")
    if len(parts) != 2:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS")
    try:
        dp, sp = int(parts[0]), int(parts[1])
    except ValueError as e:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS") from e
    if dp < 1 or sp < 1:
        raise ValueError(f"mesh shape {spec!r} must be positive")
    return dp, sp


def _mesh_shapes(spec: str, n_devices: int) -> list[tuple[int, int]]:
    """The sharded-regime mesh axis: explicit ``1x8,2x4`` shapes (kept
    only when they fit the probed device count), or ``auto`` — the flat
    data mesh plus the 2-way seq split when the count allows."""
    if spec == "auto":
        shapes = [(n_devices, 1)]
        if n_devices % 2 == 0 and n_devices > 1:
            shapes.append((n_devices // 2, 2))
        return shapes
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dp, sp = parse_mesh_shape(part)
        if dp * sp <= n_devices:
            shapes.append((dp, sp))
        else:
            print(
                f"sweep: skipping mesh {dp}x{sp} ({dp * sp} > {n_devices} "
                "visible devices)",
                file=sys.stderr,
            )
    return shapes


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(HERE, "sweep_onchip.jsonl"))
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--mesh",
        default="auto",
        help="comma-separated DxS mesh shapes for the sharded axis "
        "(e.g. 1x8,2x4); 'auto' derives from the probed device count; "
        "'' skips the sharded axis",
    )
    args = ap.parse_args()

    env = dict(os.environ)  # default env: the axon chip when healthy

    def emit(rec: dict) -> None:
        print(json.dumps(rec), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # 0) transport probe under its own watchdog — if this fails, stop early
    probe = run_config("probe", PROBE_SNIPPET, env, min(args.timeout, 300.0))
    emit(probe)
    if probe["status"] != "ok":
        print("sweep: device probe failed — tunnel down, aborting", file=sys.stderr)
        raise SystemExit(1)

    batch = 16384 if args.quick else 65536
    n_batches = 2 if args.quick else 4
    ragged_n = 2048 if args.quick else 8192

    for workers in (1, 2, 4, 8):
        emit(
            run_config(
                f"stream:batch={batch},feed_workers={workers}",
                STREAM_SNIPPET.format(
                    here=HERE, batch=batch, block=1024,
                    n_batches=n_batches, workers=workers,
                ),
                env,
                args.timeout,
            )
        )
    # batch-size axis at the best-known worker count
    for b in ((8192, 32768) if args.quick else (16384, 32768, 131072)):
        emit(
            run_config(
                f"stream:batch={b},feed_workers=4",
                STREAM_SNIPPET.format(
                    here=HERE, batch=b, block=1024,
                    n_batches=n_batches, workers=4,
                ),
                env,
                args.timeout,
            )
        )
    for pw in (1, 2, 4, 8):
        emit(
            run_config(
                f"ragged:n={ragged_n},put_workers={pw}",
                RAGGED_SNIPPET.format(here=HERE, put_workers=pw, n_articles=ragged_n),
                env,
                args.timeout,
            )
        )
    # mesh-shape axis: the sharded packed plane (per-shard fused donated
    # tiles) swept over (data, seq) factorisations × put workers, so the
    # live-tunnel window can sweep the pod-shape step without a code change
    if args.mesh:
        shapes = _mesh_shapes(args.mesh, int(probe.get("n", 1)))
        for dp, sp in shapes:
            for pw in (1, 4):
                emit(
                    run_config(
                        f"sharded:n={ragged_n},mesh={dp}x{sp},put_workers={pw}",
                        SHARDED_SNIPPET.format(
                            here=HERE, n_articles=ragged_n,
                            dp=dp, sp=sp, put_workers=pw,
                        ),
                        env,
                        args.timeout,
                    )
                )
    print(f"sweep complete -> {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
