"""On-chip knob sweep orchestrator — run the moment the tunnel is healthy.

VERDICT r3 item 1 wants BENCH_r04 captured on the chip with the ragged and
stream regimes swept over their tuning knobs (feed workers, put workers,
batch size); the rerank axis sweeps the precision tier over
(put_workers, dispatch_window, rerank_tile_rows), and its ledger rows
carry the knobs in the source tag (``sweep:rerank:n=...,put_workers=...``)
so ``obs/perfdb.parse_source_knobs`` → the engine's per-platform
knob-profile store can adopt each platform's best point automatically.
The tunnel has repeatedly died mid-session, so this driver is
built for hostile transport: every configuration runs in its OWN subprocess
under a hard watchdog, results append to a JSONL file as they land, and a
dead config (hang or transport error) is recorded and skipped rather than
taking the sweep down.

Usage:
    python tools/sweep_onchip.py                # full sweep -> sweep_onchip.jsonl
    python tools/sweep_onchip.py --quick        # 1/4-size shapes, short list
    python tools/sweep_onchip.py --out PATH --timeout 900

Interpret: take the best stream/ragged rows, set
``ASTPU_BENCH_FEED_WORKERS`` / ``ASTPU_DEDUP_PUT_WORKERS`` /
``ASTPU_BENCH_BATCH`` accordingly, then run ``python bench.py`` for the
round record.

Every successful sweep point also lands in the perf ledger
(``obs/perfdb.py``; ``--ledger``, default ``$ASTPU_PERF_LEDGER`` or
``<out>.ledger.jsonl``) stamped with the probed platform — so the first
tunnel window auto-produces comparable same-platform history instead of
one more orphaned JSONL.  After the grid, the best point of each regime
re-runs ONCE under ``ASTPU_TRACE_DIR`` (``--trace-dir``; '' disables) so
each regime's best configuration leaves an XLA trace to read against its
rate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE_SNIPPET = (
    "import jax, json; d = jax.devices(); "
    "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
)

STREAM_SNIPPET = """
import json, os, sys, threading, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.core.hashing import make_params
from advanced_scrapper_tpu.core.mesh import build_mesh
from advanced_scrapper_tpu.cpu.hostbatch import HostBatcher
from advanced_scrapper_tpu.obs.profiler import xla_trace
from advanced_scrapper_tpu.parallel.sharded import make_sharded_dedup, shard_batch
from advanced_scrapper_tpu.pipeline.feed import DeviceFeed

batch, block, n_batches, workers = {batch}, {block}, {n_batches}, {workers}
params = make_params()
mesh = build_mesh(len(jax.devices()), 1)
base, docs = bench._stream_corpus(batch, block)
step = make_sharded_dedup(mesh, params, backend="scan")
warm = shard_batch(base, np.full((batch,), block, np.int32), mesh)
jax.block_until_ready(step(*warm))
batcher = HostBatcher(block)
feed = DeviceFeed(batcher, batch, depth=4, workers=workers)
def produce():
    for b in range(n_batches):
        batcher.feed(docs, start_tag=b * batch, chunk=4096)
    batcher.close()
t0 = time.perf_counter()
threading.Thread(target=produce, daemon=True).start()
pending = []
# ASTPU_TRACE_DIR (the best-point re-run sets it): the measured region
# leaves an XLA trace; unset = xla_trace is a no-op
with xla_trace(os.environ.get("ASTPU_TRACE_DIR") or None):
    for n, tok_dev, len_dev, tags in feed:
        rep, _h = step(tok_dev, len_dev)
        try:
            rep.copy_to_host_async()   # same readback overlap as bench._bench_stream
        except AttributeError:
            pass
        pending.append((rep, tags, n))
    outs = [tags[np.asarray(rep)[:n]] for rep, tags, n in pending]
dt = time.perf_counter() - t0
feed.join()
total = batch * n_batches
assert sum(o.shape[0] for o in outs) == total
print(json.dumps({{"articles_per_sec": round(total / dt, 1)}}))
"""

RAGGED_SNIPPET = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

n = {n_articles}
rng = np.random.RandomState(7)
# explicit config: NearDupEngine() raw defaults ignore env knobs
engine = NearDupEngine(DedupConfig(put_workers={put_workers}))
engine.dedup_reps(bench._ragged_corpus(rng, n))      # warm all shapes
corpus = bench._ragged_corpus(rng, n)
from advanced_scrapper_tpu.obs.profiler import xla_trace
with xla_trace(os.environ.get("ASTPU_TRACE_DIR") or None):
    t0 = time.perf_counter()
    rep = np.asarray(engine.dedup_reps_async(corpus))[:n]
    dt = time.perf_counter() - t0
print(json.dumps({{"articles_per_sec": round(n / dt, 1)}}))
"""

RERANK_SNIPPET = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {here!r})
# the swept pins must be authoritative: the engine's knob-profile
# resolver honors env > pin > ledger-best, so a stray knob env (or the
# sweep's own ledger) would silently collapse the grid to one point
for _k in (
    "ASTPU_PERF_LEDGER", "ASTPU_DEDUP_PUT_WORKERS",
    "ASTPU_DEDUP_DISPATCH_WINDOW", "ASTPU_DEDUP_RERANK_TILE_ROWS",
    "ASTPU_DEDUP_RERANK",
):
    os.environ.pop(_k, None)
import jax
import bench
from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

n = {n_articles}
rng = np.random.RandomState(11)
engine = NearDupEngine(DedupConfig(
    rerank=True, put_workers={put_workers}, dispatch_window={window},
    rerank_tile_rows={tile_rows},
))
engine.prewarm(n)                      # compile the settle-tile shape set
engine.dedup_reps(bench._rerank_corpus(rng, n))   # warm the full path
corpus = bench._rerank_corpus(rng, n)
from advanced_scrapper_tpu.obs.profiler import xla_trace
with xla_trace(os.environ.get("ASTPU_TRACE_DIR") or None):
    t0 = time.perf_counter()
    rep = engine.dedup_reps(corpus)[:n]
    dt = time.perf_counter() - t0
print(json.dumps({{
    "articles_per_sec": round(n / dt, 1),
    "rerank_tiles": int(engine.rerank_tier.stats.get("tiles", 0)),
    "rerank_pairs": int(engine.rerank_tier.stats.get("pairs", 0)),
}}))
"""

SHARDED_SNIPPET = """
import json, os, sys, time
import numpy as np
sys.path.insert(0, {here!r})
import jax
import bench
from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.mesh import build_mesh
from advanced_scrapper_tpu.obs import stages
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

n, dp, sp = {n_articles}, {dp}, {sp}
rng = np.random.RandomState(7)
engine = NearDupEngine(DedupConfig(put_workers={put_workers}))
# sub-count shapes (dp*sp < devices) sweep a carved sub-mesh: build_mesh
# requires len(devices) == dp*sp, so hand it exactly that many
mesh = build_mesh(dp, sp, devices=jax.devices()[: dp * sp])
engine.prewarm_sharded(mesh, n)                       # warm the shape set
engine.dedup_reps_sharded(bench._ragged_corpus(rng, n), mesh)
corpus = bench._ragged_corpus(rng, n)
ps0 = stages.sharded_device_counters()
from advanced_scrapper_tpu.obs.profiler import xla_trace
with xla_trace(os.environ.get("ASTPU_TRACE_DIR") or None):
    t0 = time.perf_counter()
    rep = engine.dedup_reps_sharded(corpus, mesh)
    dt = time.perf_counter() - t0
ps1 = stages.sharded_device_counters()
puts = sorted(
    ps1[s]["device_puts"] - ps0.get(s, {{}}).get("device_puts", 0.0)
    for s in ps1
)
print(json.dumps({{
    "articles_per_sec": round(n / dt, 1),
    "mesh": [dp, sp],
    "tiles": engine.last_tiles,
    "per_shard_puts": [puts[0], puts[-1]],
}}))
"""


def run_config(tag: str, snippet: str, env: dict, timeout: float) -> dict:
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", snippet],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=HERE,
        )
    except subprocess.TimeoutExpired:
        return {"config": tag, "status": "timeout", "elapsed_s": round(time.time() - t0, 1)}
    rec: dict = {
        "config": tag,
        "status": "ok" if proc.returncode == 0 else "error",
        "elapsed_s": round(time.time() - t0, 1),
    }
    if proc.returncode == 0:
        try:
            rec.update(json.loads(proc.stdout.strip().splitlines()[-1]))
        except (ValueError, IndexError):
            rec["status"] = "unparseable"
            rec["stdout_tail"] = proc.stdout[-300:]
    else:
        rec["stderr_tail"] = proc.stderr[-300:]
    return rec


def parse_mesh_shape(spec: str) -> tuple[int, int]:
    """``"2x4"`` → ``(2, 4)`` — local twin of
    ``core.mesh.parse_mesh_shape`` (same DxS grammar, asserted in
    tests).  Deliberately NOT imported from the package: this parent
    process must never import jax (a dead tunnel can hang backend-
    touching imports forever; every jax-touching config runs in its own
    watchdogged subprocess)."""
    parts = spec.lower().strip().split("x")
    if len(parts) != 2:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS")
    try:
        dp, sp = int(parts[0]), int(parts[1])
    except ValueError as e:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS") from e
    if dp < 1 or sp < 1:
        raise ValueError(f"mesh shape {spec!r} must be positive")
    return dp, sp


def _mesh_shapes(spec: str, n_devices: int) -> list[tuple[int, int]]:
    """The sharded-regime mesh axis: explicit ``1x8,2x4`` shapes (kept
    only when they fit the probed device count), or ``auto`` — the flat
    data mesh plus the 2-way seq split when the count allows."""
    if spec == "auto":
        shapes = [(n_devices, 1)]
        if n_devices % 2 == 0 and n_devices > 1:
            shapes.append((n_devices // 2, 2))
        return shapes
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dp, sp = parse_mesh_shape(part)
        if dp * sp <= n_devices:
            shapes.append((dp, sp))
        else:
            print(
                f"sweep: skipping mesh {dp}x{sp} ({dp * sp} > {n_devices} "
                "visible devices)",
                file=sys.stderr,
            )
    return shapes


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(HERE, "sweep_onchip.jsonl"))
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--mesh",
        default="auto",
        help="comma-separated DxS mesh shapes for the sharded axis "
        "(e.g. 1x8,2x4); 'auto' derives from the probed device count; "
        "'' skips the sharded axis",
    )
    ap.add_argument(
        "--ledger",
        default=os.environ.get("ASTPU_PERF_LEDGER") or "",
        help="perf-ledger JSONL every successful sweep point appends to "
        "(default: $ASTPU_PERF_LEDGER, else <out>.ledger.jsonl; "
        "tools/perf_ledger.py report reads it)",
    )
    ap.add_argument(
        "--trace-dir",
        default=os.path.join(HERE, "sweep_traces"),
        help="after the grid, re-run each regime's best point once under "
        "ASTPU_TRACE_DIR=<trace-dir>/<regime> to capture an XLA trace "
        "('' disables)",
    )
    args = ap.parse_args()
    ledger_path = args.ledger or (args.out + ".ledger.jsonl")

    env = dict(os.environ)  # default env: the axon chip when healthy
    # jax-free by construction: obs.perfdb is stdlib-only, and this
    # parent must never touch a backend import (a dead tunnel hangs them)
    from advanced_scrapper_tpu.obs import perfdb

    ledger = perfdb.PerfLedger(ledger_path)
    git = perfdb.git_sha(HERE)
    platform = "unknown"
    #: regime → [(rate, tag, snippet)] for the best-point trace pass
    by_regime: dict[str, list] = {}

    def emit(rec: dict, snippet: str | None = None) -> None:
        print(json.dumps(rec), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")
        rate = rec.get("articles_per_sec")
        if rec.get("status") != "ok" or not isinstance(rate, (int, float)):
            return
        if rec["config"].endswith(":trace"):
            # the best-point trace re-run pays jax.profiler overhead —
            # ledgering it as the newest same-platform row would read as
            # a fresh regression caused by the sweep's own tracing pass
            return
        regime = rec["config"].split(":", 1)[0]
        if snippet is not None:
            by_regime.setdefault(regime, []).append(
                (float(rate), rec["config"], snippet)
            )
        try:
            ledger.append(
                {
                    "schema": perfdb.SCHEMA,
                    "kind": "sweep",
                    "source": f"sweep:{rec['config']}",
                    # None, not inf: json.dumps(inf) emits the
                    # non-standard Infinity token (perfdb._round_order
                    # has the same rule); None sorts after every rNN row
                    "order": None,
                    "ts": time.time(),
                    "platform": platform,
                    "fingerprint": None,
                    "git_sha": git,
                    "metrics": {
                        f"{regime}_articles_per_sec": float(rate),
                    },
                }
            )
        except OSError as e:
            print(f"sweep: ledger append failed: {e}", file=sys.stderr)

    # 0) transport probe under its own watchdog — if this fails, stop early
    probe = run_config("probe", PROBE_SNIPPET, env, min(args.timeout, 300.0))
    emit(probe)
    if probe["status"] != "ok":
        print("sweep: device probe failed — tunnel down, aborting", file=sys.stderr)
        raise SystemExit(1)
    # the ledger's platform partition: same grammar as the bench
    # fingerprint key, so sweep points and bench rounds on the same
    # transport compare (and cpu dev-box dryruns never do)
    platform = f"{probe.get('platform', 'unknown')}/swept-x{probe.get('n', '?')}"

    batch = 16384 if args.quick else 65536
    n_batches = 2 if args.quick else 4
    ragged_n = 2048 if args.quick else 8192

    for workers in (1, 2, 4, 8):
        snip = STREAM_SNIPPET.format(
            here=HERE, batch=batch, block=1024,
            n_batches=n_batches, workers=workers,
        )
        emit(
            run_config(
                f"stream:batch={batch},feed_workers={workers}",
                snip, env, args.timeout,
            ),
            snip,
        )
    # batch-size axis at the best-known worker count
    for b in ((8192, 32768) if args.quick else (16384, 32768, 131072)):
        snip = STREAM_SNIPPET.format(
            here=HERE, batch=b, block=1024,
            n_batches=n_batches, workers=4,
        )
        emit(
            run_config(
                f"stream:batch={b},feed_workers=4", snip, env, args.timeout
            ),
            snip,
        )
    for pw in (1, 2, 4, 8):
        snip = RAGGED_SNIPPET.format(
            here=HERE, put_workers=pw, n_articles=ragged_n
        )
        emit(
            run_config(
                f"ragged:n={ragged_n},put_workers={pw}", snip, env,
                args.timeout,
            ),
            snip,
        )
    # precision-tier axis: the rerank regime over (put_workers, window,
    # tile_rows).  The config tag's k=v tail is the ledger-source grammar
    # obs/perfdb.parse_source_knobs reads back, so the engine's
    # per-platform knob-profile store adopts each platform's best point
    # automatically (pipeline.dedup._resolve_knob_profile)
    rr_grid = (
        ((1, 2, 512), (4, 6, 1024))
        if args.quick
        else tuple(
            (pw, win, tr)
            for pw in (1, 4)
            for win in (2, 6)
            for tr in (512, 1024, 2048)
        )
    )
    for pw, win, tr in rr_grid:
        snip = RERANK_SNIPPET.format(
            here=HERE, n_articles=ragged_n,
            put_workers=pw, window=win, tile_rows=tr,
        )
        emit(
            run_config(
                f"rerank:n={ragged_n},put_workers={pw},window={win},"
                f"tile_rows={tr}",
                snip, env, args.timeout,
            ),
            snip,
        )
    # mesh-shape axis: the sharded packed plane (per-shard fused donated
    # tiles) swept over (data, seq) factorisations × put workers, so the
    # live-tunnel window can sweep the pod-shape step without a code change
    if args.mesh:
        shapes = _mesh_shapes(args.mesh, int(probe.get("n", 1)))
        for dp, sp in shapes:
            for pw in (1, 4):
                snip = SHARDED_SNIPPET.format(
                    here=HERE, n_articles=ragged_n,
                    dp=dp, sp=sp, put_workers=pw,
                )
                emit(
                    run_config(
                        f"sharded:n={ragged_n},mesh={dp}x{sp},put_workers={pw}",
                        snip, env, args.timeout,
                    ),
                    snip,
                )

    # best-point XLA traces: one re-run per regime at its winning config,
    # with ASTPU_TRACE_DIR plumbed through the snippet's xla_trace wrap —
    # the tunnel window's sweep leaves a kernel timeline to read against
    # each best rate, not just a number
    if args.trace_dir:
        for regime, entries in sorted(by_regime.items()):
            rate, tag, snip = max(entries, key=lambda e: e[0])
            tdir = os.path.join(args.trace_dir, regime)
            os.makedirs(tdir, exist_ok=True)
            tenv = dict(env, ASTPU_TRACE_DIR=tdir)
            rec = run_config(f"{tag}:trace", snip, tenv, args.timeout)
            rec["trace_dir"] = tdir
            rec["traced_best_of"] = {"config": tag, "articles_per_sec": rate}
            emit(rec)
    print(f"sweep complete -> {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
