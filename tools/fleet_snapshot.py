#!/usr/bin/env python
"""Fleet-wide consistent snapshot + byte-identical restore.

The disaster-recovery half of the self-healing plane: scrub/repair heal a
fleet that is still standing; this tool is for the fleet that is NOT —
region loss, bulk operator error, a migration.  It speaks the shard RPC
plane, so it works against a LIVE fleet:

**snapshot** — per shard, per space, the ``snapshot`` RPC fences the WAL
(the shard cuts its memtable under the write lock, so the durable state
collapses to manifest + immutable segments) and returns the pinned
manifest plus every live file's size and whole-file digest.  Files stream
back through paged ``fetch_file`` frames (immutable, so pages always
compose) and every byte is digest-verified on arrival; a file swept by a
racing compaction fails its digest and the shard is re-fenced (bounded
retries).  The last write is the **manifest of manifests**
(``MANIFEST.json``, atomic) — a snapshot directory without it is garbage
by definition, so a killed snapshot can never masquerade as a whole one.

**restore** — materialises the snapshot onto fresh per-node index
directories (every replica of a shard gets identical bytes — replicas ARE
byte-identical after restore), re-verifying every digest after the copy.
Start shard servers on the restored directories and the fleet answers
probes exactly as the snapshotted one did.

**verify** — offline digest sweep of a snapshot directory.

Usage::

    python tools/fleet_snapshot.py snapshot --fleet "h:p|h:p;h:p" --out SNAP
    python tools/fleet_snapshot.py restore  --snapshot SNAP --out BASE [--replicas 2]
    python tools/fleet_snapshot.py verify   --snapshot SNAP

``--fleet`` uses the ``DedupConfig.index_fleet`` wire syntax; the primary
(first replica) of each shard is snapshotted — by the live-node invariant
any live node holds every acked posting, and a quiesced fleet's replicas
are semantically identical.  Snapshot consistency across SHARDS assumes a
quiesced ingest (fence order is per-shard); for a moving fleet, pause the
writers for the fence beat — the fence itself is one cut per shard.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SNAP_MANIFEST = "MANIFEST.json"
DEFAULT_SPACES = ("bands", "urls")
FETCH_PAGE = 4 << 20  # 4 MiB per fetch_file frame — far under the RPC cap


def _digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _file_digest(path: str) -> str:
    """Chunked on-disk digest — the ONE identity definition, shared with
    the manifest recorder (``index.segment.file_digest``); multi-GB
    segments never round-trip through RAM here."""
    from advanced_scrapper_tpu.index.segment import file_digest

    return file_digest(path)


def _copy_verified(src: str, dst: str, want: str) -> None:
    """Stream ``src`` → ``dst`` atomically (1 MiB chunks), then re-verify
    the landed bytes against ``want``."""
    from advanced_scrapper_tpu.storage.fsio import atomic_write

    def writer(fh):
        with open(src, "rb") as sf:
            while True:
                chunk = sf.read(1 << 20)
                if not chunk:
                    break
                fh.write(chunk)

    atomic_write(dst, writer)
    if _file_digest(dst) != want:
        raise RuntimeError(f"{dst}: digest mismatch after copy")


def snapshot_fleet(
    fleet: str,
    out_dir: str,
    *,
    spaces=DEFAULT_SPACES,
    timeout: float = 10.0,
    retries: int = 2,
    fence_retries: int = 3,
    allow_reshard: bool = False,
) -> dict:
    """Pull a consistent snapshot of every shard into ``out_dir``;
    returns the manifest-of-manifests dict (also written atomically as
    ``MANIFEST.json``, the commit point).

    A shard carrying a live reshard fence mark is REFUSED (unless
    ``allow_reshard``): mid-cutover, range ownership is split between the
    old and new rings and a per-shard snapshot would freeze half-migrated
    state that no single ring can serve — finish (or void) the cutover,
    then snapshot."""
    from advanced_scrapper_tpu.index.fleet import FleetSpec
    from advanced_scrapper_tpu.index.remote import RemoteIndex
    from advanced_scrapper_tpu.storage.fsio import atomic_replace, atomic_write

    spec = fleet if isinstance(fleet, FleetSpec) else FleetSpec.parse(fleet)
    os.makedirs(out_dir, exist_ok=True)
    manifest: dict = {"version": 1, "spaces": list(spaces), "shards": []}
    for sid, nodes in enumerate(spec.shards):
        shard_entry: dict = {"shard": sid, "source": f"{nodes[0][0]}:{nodes[0][1]}",
                             "spaces": {}}
        for space in spaces:
            remote = RemoteIndex(
                nodes[0], space=space, timeout=timeout, retries=retries
            )
            try:
                for attempt in range(fence_retries):
                    meta = remote.snapshot_meta()
                    mark = meta["manifest"].get("reshard")
                    if mark and not allow_reshard:
                        raise RuntimeError(
                            f"shard {sid} space {space} is fenced by a live "
                            f"reshard (mark {mark}): a mid-cutover snapshot "
                            "would freeze half-migrated ownership — finish or "
                            "void the cutover first (--allow-mid-reshard to "
                            "override)"
                        )
                    sdir = os.path.join(out_dir, f"s{sid}", space)
                    os.makedirs(sdir, exist_ok=True)
                    ok = True
                    for f in meta["files"]:
                        # stream pages straight to disk (bounded memory)
                        # then digest the landed bytes chunked
                        target = os.path.join(sdir, f["name"])
                        atomic_write(
                            target,
                            lambda fh, name=f["name"]: remote.fetch_file_into(
                                name, fh, page=FETCH_PAGE
                            ),
                        )
                        if _file_digest(target) != f["digest"]:
                            # a racing compaction superseded the file
                            # mid-stream: re-fence and retry the space
                            os.unlink(target)
                            ok = False
                            break
                    if ok:
                        break
                else:
                    raise RuntimeError(
                        f"shard {sid} space {space}: files kept changing "
                        f"under the snapshot across {fence_retries} fences "
                        "(quiesce the ingest)"
                    )
                man_bytes = json.dumps(meta["manifest"], indent=1).encode()
                atomic_replace(os.path.join(sdir, "manifest.json"), man_bytes)
                shard_entry["spaces"][space] = {
                    "manifest": meta["manifest"],
                    "manifest_digest": _digest(man_bytes),
                    "files": {f["name"]: f["digest"] for f in meta["files"]},
                }
            finally:
                remote.close()
        manifest["shards"].append(shard_entry)
    # the commit point: a snapshot directory is whole iff this exists
    atomic_replace(
        os.path.join(out_dir, SNAP_MANIFEST),
        json.dumps(manifest, indent=1).encode(),
    )
    return manifest


def verify_snapshot(snap_dir: str) -> list[str]:
    """Offline digest sweep; returns problems (empty = intact)."""
    problems: list[str] = []
    man_path = os.path.join(snap_dir, SNAP_MANIFEST)
    if not os.path.exists(man_path):
        return [f"{SNAP_MANIFEST} missing — snapshot never committed"]
    with open(man_path) as fh:
        manifest = json.load(fh)
    for shard in manifest.get("shards", []):
        sid = shard["shard"]
        for space, entry in shard.get("spaces", {}).items():
            sdir = os.path.join(snap_dir, f"s{sid}", space)
            for name, want in entry.get("files", {}).items():
                path = os.path.join(sdir, name)
                if not os.path.exists(path):
                    problems.append(f"s{sid}/{space}/{name}: missing")
                    continue
                if _file_digest(path) != want:
                    problems.append(f"s{sid}/{space}/{name}: digest mismatch")
            mpath = os.path.join(sdir, "manifest.json")
            if not os.path.exists(mpath):
                problems.append(f"s{sid}/{space}/manifest.json: missing")
            else:
                with open(mpath, "rb") as fh:
                    if _digest(fh.read()) != entry.get("manifest_digest"):
                        problems.append(
                            f"s{sid}/{space}/manifest.json: digest mismatch"
                        )
    return problems


def restore_fleet(
    snap_dir: str, out_base: str, *, replicas: int = 1
) -> list[str]:
    """Materialise the snapshot onto fresh node directories
    (``out_base/s<sid>n<rep>/<space>/``), digest-verifying every copied
    byte; returns the node directories created.  Refuses non-empty
    targets — restore never silently merges into existing state."""
    from advanced_scrapper_tpu.storage.fsio import atomic_replace

    problems = verify_snapshot(snap_dir)
    if problems:
        raise RuntimeError(f"snapshot {snap_dir} failed verification: {problems}")
    with open(os.path.join(snap_dir, SNAP_MANIFEST)) as fh:
        manifest = json.load(fh)
    created: list[str] = []
    for shard in manifest["shards"]:
        sid = shard["shard"]
        for rep in range(replicas):
            node_dir = os.path.join(out_base, f"s{sid}n{rep}")
            for space, entry in shard["spaces"].items():
                tdir = os.path.join(node_dir, space)
                if os.path.isdir(tdir) and os.listdir(tdir):
                    raise RuntimeError(
                        f"restore target {tdir} is not empty — refusing to "
                        "merge a snapshot into existing state"
                    )
                os.makedirs(tdir, exist_ok=True)
                sdir = os.path.join(snap_dir, f"s{sid}", space)
                for name, want in entry["files"].items():
                    _copy_verified(
                        os.path.join(sdir, name),
                        os.path.join(tdir, name),
                        want,
                    )
                with open(os.path.join(sdir, "manifest.json"), "rb") as fh:
                    man_bytes = fh.read()
                # the manifest lands LAST — the restore's commit point per
                # space, mirroring the index's own cut discipline
                atomic_replace(os.path.join(tdir, "manifest.json"), man_bytes)
            created.append(node_dir)
    return created


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("snapshot", help="pull a snapshot from a live fleet")
    s.add_argument("--fleet", required=True, help="h:p|h:p;h:p spec")
    s.add_argument("--out", required=True, help="snapshot directory")
    s.add_argument("--spaces", default=",".join(DEFAULT_SPACES))
    s.add_argument("--timeout", type=float, default=10.0)
    s.add_argument(
        "--allow-mid-reshard", action="store_true",
        help="snapshot even through a live reshard fence mark (the "
             "result freezes half-migrated ownership — restore it only "
             "with the matching migration WAL in hand)",
    )
    r = sub.add_parser("restore", help="materialise onto fresh node dirs")
    r.add_argument("--snapshot", required=True)
    r.add_argument("--out", required=True, help="base dir for node dirs")
    r.add_argument("--replicas", type=int, default=1)
    v = sub.add_parser("verify", help="offline digest sweep")
    v.add_argument("--snapshot", required=True)
    args = ap.parse_args(argv)

    if args.cmd == "snapshot":
        man = snapshot_fleet(
            args.fleet, args.out,
            spaces=tuple(s for s in args.spaces.split(",") if s),
            timeout=args.timeout,
            allow_reshard=args.allow_mid_reshard,
        )
        n_files = sum(
            len(e["files"]) for sh in man["shards"] for e in sh["spaces"].values()
        )
        print(
            f"snapshot committed: {len(man['shards'])} shards, "
            f"{n_files} files → {args.out}"
        )
        return 0
    if args.cmd == "restore":
        dirs = restore_fleet(args.snapshot, args.out, replicas=args.replicas)
        print(f"restored {len(dirs)} node dirs:")
        for d in dirs:
            print(f"  {d}")
        return 0
    problems = verify_snapshot(args.snapshot)
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if not problems:
        print("snapshot intact")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
