#!/usr/bin/env python
"""Offline persistent-index verifier: every CRC, every digest, every frame.

``PersistentIndex.scrub()`` is the ONLINE integrity pass; this is its
offline twin for directories no process owns — a crashsweep post-condition,
a pre-restore snapshot check, an operator's "is this disk still good".
For each index directory (anything holding a ``manifest.json``):

- the manifest parses and names only files that exist;
- every segment opens (header CRC, CRC-table CRC, structural size),
  passes a FULL block-CRC sweep over all three body planes, and matches
  its manifest-recorded whole-file digest (v1 segments — no CRC table —
  verify structure + digest only, reported as such);
- the live WAL generation is well-framed (``replay_wal``): a torn TAIL is
  a normal crash artifact (reported, not an error — the next writable
  open truncates it), but bytes the replay cannot reach are counted;
- orphan ``.seg``/``wal-*`` files and ``.quarantine`` sidecars are listed
  as informational findings.

Exit status: 0 when every directory verified clean, 1 when any corruption
was found (nonzero-exit per-file report — the crashsweep ``bitrot``
workload's final gate).  Read-only by construction: fsck never repairs,
quarantines or truncates — that is the writable open's / scrub's job.

Usage::

    python tools/fsck_index.py DIR [DIR ...] [--json]

A DIR may be an index directory itself or any ancestor — every
``manifest.json`` found below it is checked.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def find_index_dirs(root: str) -> list[str]:
    """Every directory at-or-below ``root`` holding a ``manifest.json``."""
    if os.path.isfile(os.path.join(root, "manifest.json")):
        return [root]
    out = []
    for dirpath, _dirs, files in os.walk(root):
        if "manifest.json" in files:
            out.append(dirpath)
    return sorted(out)


def fsck_dir(directory: str, fs=None) -> dict:
    """Verify one index directory; returns a report dict with
    ``problems`` (corruption — nonzero exit) and ``notes``
    (informational: torn WAL tails, quarantine sidecars, orphans)."""
    from advanced_scrapper_tpu.index.segment import Segment, SegmentCorruption
    from advanced_scrapper_tpu.index.wal import replay_wal
    from advanced_scrapper_tpu.storage.fsio import default_fs

    fs = fs or default_fs()
    report: dict = {
        "dir": directory,
        "segments": 0,
        "postings": 0,
        "wal_postings": 0,
        "problems": [],
        "notes": [],
    }
    man_path = os.path.join(directory, "manifest.json")
    try:
        with fs.open(man_path, "rb") as fh:
            man = json.loads(fh.read().decode("utf-8"))
    except (OSError, ValueError) as e:
        report["problems"].append(f"manifest.json unreadable: {e}")
        return report
    if int(man.get("version", 1)) != 1:
        report["problems"].append(
            f"unknown manifest version {man.get('version')}"
        )
        return report
    # elastic-reshard context: handed-off arcs mean this node's on-disk
    # postings legitimately EXCEED its semantic read surface — another
    # node owns those ranges now, so a fleet-wide count treating them as
    # live would read as duplication, and treating their absence from
    # reads as loss would be just as wrong.  Both are notes, not errors.
    handed = man.get("handed_off") or []
    if handed:
        report["notes"].append(
            f"{len(handed)} ring range(s) handed off to another owner "
            "(migrated away — excluded from semantic reads, not a loss)"
        )
    mark = man.get("reshard")
    if mark:
        report["notes"].append(
            f"reshard fence mark present (token {mark.get('token')!r}) — "
            "a cutover was live at the last manifest write"
        )
    digests = man.get("digests", {})
    live = set(man.get("segments", []))
    for name in man.get("segments", []):
        path = os.path.join(directory, name)
        if not fs.exists(path):
            report["problems"].append(f"{name}: manifest names a missing file")
            continue
        try:
            seg = Segment(path, fs=fs)
        except SegmentCorruption as e:
            report["problems"].append(f"{name}: {e.detail}")
            continue
        except (OSError, ValueError) as e:
            report["problems"].append(f"{name}: unopenable ({e})")
            continue
        try:
            digest = seg.verify_all(fs=fs)
        except SegmentCorruption as e:
            report["problems"].append(f"{name}: {e.detail}")
            seg.close()
            continue
        report["segments"] += 1
        report["postings"] += seg.count
        want = digests.get(name)
        if want is None:
            report["notes"].append(
                f"{name}: no manifest digest recorded "
                f"({'v1 segment' if seg.version == 1 else 'pre-digest manifest'})"
            )
        elif want != digest:
            report["problems"].append(
                f"{name}: whole-file digest mismatch ({digest} != "
                f"manifest {want})"
            )
        if seg.version == 1:
            report["notes"].append(
                f"{name}: v1 format — no block CRCs to verify"
            )
        seg.close()
    wal_name = f"wal-{int(man.get('wal_seq', 0)):08d}.log"
    wal_path = os.path.join(directory, wal_name)
    if fs.exists(wal_path):
        keys, _docs, valid_end = replay_wal(wal_path, fs=fs)
        report["wal_postings"] = int(keys.size)
        size = fs.size(wal_path)
        if size > valid_end:
            report["notes"].append(
                f"{wal_name}: torn tail ({size - valid_end} bytes past the "
                "last whole frame — truncated by the next writable open)"
            )
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        names = []
    for name in names:
        if name.endswith(".quarantine"):
            report["notes"].append(f"{name}: quarantine sidecar present")
        elif name.endswith(".seg") and name not in live:
            report["notes"].append(f"{name}: orphan segment (not in manifest)")
        elif (
            name.startswith("wal-") and name.endswith(".log")
            and name != wal_name
        ):
            report["notes"].append(f"{name}: superseded WAL generation")
    report["ok"] = not report["problems"]
    return report


def fsck(paths, fs=None) -> dict:
    """Walk every given path for index dirs; aggregate report."""
    reports = []
    for p in paths:
        dirs = find_index_dirs(p)
        if not dirs:
            reports.append(
                {"dir": p, "problems": [f"no manifest.json found under {p}"],
                 "notes": [], "ok": False}
            )
            continue
        for d in dirs:
            reports.append(fsck_dir(d, fs=fs))
    return {
        "dirs": reports,
        "problems": [
            f"{r['dir']}: {p}" for r in reports for p in r["problems"]
        ],
        "ok": all(r.get("ok") for r in reports),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dirs", nargs="+", help="index dirs (or ancestors)")
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    args = ap.parse_args(argv)
    report = fsck(args.dirs)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for r in report["dirs"]:
            verdict = "clean" if r.get("ok") else "CORRUPT"
            print(
                f"{r['dir']}: {verdict} "
                f"({r.get('segments', 0)} segments, "
                f"{r.get('postings', 0)} postings, "
                f"{r.get('wal_postings', 0)} WAL postings)"
            )
            for p in r["problems"]:
                print(f"  PROBLEM: {p}")
            for n in r["notes"]:
                print(f"  note: {n}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
