"""Elastic rate control: proportional and asymmetric-PID worker scaling.

The reference explored three admission-control designs before settling on
fixed-rate feeding (SURVEY.md §2.4): a P-controller on thread count
(``experiental/local_dynamic.py:196-233``, ``delta = int(0.5·error)`` every
0.5 s) and a full PID with asymmetric accel/decel gains
(``experiental/local_pid.py:42-89,246-279``, accel ``Kp=0.5`` vs decel
``Kp=1.0``, wall-clock integral, 0.8 s cadence, floor 1 / cap MAX_THREADS).
Both are reproduced here as controllers plus an :class:`ElasticWorkerPool`
that grows/shrinks a thread pool toward a target request rate — the same
elastic-scaling capability, usable with any worker body.

The PID keeps the reference's quirk of switching gain sets on the *sign of
the error* (push hard when over target, gently when under), which is the
part that made it the repo's most sophisticated rate design.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable

from advanced_scrapper_tpu.obs.stats import StatsTracker


class PController:
    """Proportional thread-count controller (ref local_dynamic.py:196-201)."""

    def __init__(self, setpoint: float, kp: float = 0.5):
        self.setpoint = setpoint
        self.kp = kp

    def compute(self, actual_rate: float) -> float:
        return self.kp * (self.setpoint - actual_rate)


class PIDController:
    """Asymmetric-gain PID (ref local_pid.py:42-89).

    Positive error (below target) uses the accel gains; negative error uses
    the decel gains.  The integral accumulates error·wall-time; the
    derivative is Δerror/Δt.
    """

    def __init__(
        self,
        setpoint: float,
        kp_accel: float = 0.5,
        ki_accel: float = 0.0,
        kd_accel: float = 0.0,
        kp_decel: float = 1.0,
        ki_decel: float = 0.0,
        kd_decel: float = 0.0,
        clock=time.time,
    ):
        self.setpoint = setpoint
        self.kp_accel, self.ki_accel, self.kd_accel = kp_accel, ki_accel, kd_accel
        self.kp_decel, self.ki_decel, self.kd_decel = kp_decel, ki_decel, kd_decel
        self._clock = clock
        self._lock = threading.Lock()
        self._last_time: float | None = None
        self._last_error = 0.0
        self._integral = 0.0

    def compute(self, actual_rate: float) -> float:
        with self._lock:
            now = self._clock()
            error = self.setpoint - actual_rate
            dt = now - self._last_time if self._last_time is not None else 0.0
            de = error - self._last_error
            if error >= 0:
                kp, ki, kd = self.kp_accel, self.ki_accel, self.kd_accel
            else:
                kp, ki, kd = self.kp_decel, self.ki_decel, self.kd_decel
            derivative = de / dt if dt > 0 else 0.0
            self._integral += error * dt
            self._last_time = now
            self._last_error = error
            return kp * error + ki * self._integral + kd * derivative


@dataclass
class PoolLimits:
    min_threads: int = 1    # ref local_pid.py:256 floor
    max_threads: int = 12   # ref local_pid.py:22


class ElasticWorkerPool:
    """Grow/shrink a worker-thread pool toward a target rate.

    ``worker_body(stop_event)`` is the per-thread loop (the engine passes a
    closure over its queues).  The monitor applies the controller output as
    a thread-count delta every ``interval`` seconds, clamped to limits
    (ref local_dynamic.py:203-233 / local_pid.py:246-279).
    """

    def __init__(
        self,
        controller,
        stats: StatsTracker,
        worker_body: Callable[[threading.Event], None],
        *,
        limits: PoolLimits | None = None,
        interval: float = 0.8,  # ref local_pid.py:279
        sleep=time.sleep,
    ):
        self.controller = controller
        self.stats = stats
        self.worker_body = worker_body
        self.limits = limits or PoolLimits()
        self.interval = interval
        self.sleep = sleep
        self._lock = threading.Lock()
        self._workers: list[tuple[threading.Thread, threading.Event]] = []
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self.adjustments: list[int] = []  # observed deltas (for tests/obs)

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._workers)

    def _spawn(self) -> None:
        ev = threading.Event()
        t = threading.Thread(target=self.worker_body, args=(ev,), daemon=True)
        t.start()
        self._workers.append((t, ev))

    def _reap(self) -> None:
        t, ev = self._workers.pop()
        ev.set()
        t.join(timeout=5)

    def step(self) -> int:
        """One control step; returns the applied thread delta."""
        output = self.controller.compute(self.stats.get_actual_rate())
        reaped: list[tuple[threading.Thread, threading.Event]] = []
        with self._lock:
            current = len(self._workers)
            desired = max(
                self.limits.min_threads,
                min(current + int(output), self.limits.max_threads),
            )
            delta = desired - current
            for _ in range(max(0, delta)):
                self._spawn()
            for _ in range(max(0, -delta)):
                reaped.append(self._workers.pop())
        # stop + join outside the lock: a mid-fetch worker must not stall the
        # monitor, size, or stop for up to 5 s per reaped thread
        for _, ev in reaped:
            ev.set()
        for t, _ in reaped:
            t.join(timeout=5)
        self.adjustments.append(delta)
        return delta

    def start(self, initial_threads: int = 1) -> "ElasticWorkerPool":
        with self._lock:
            for _ in range(max(self.limits.min_threads, initial_threads)):
                self._spawn()

        def monitor():
            while not self._stop.is_set():
                self.step()
                self.sleep(self.interval)

        self._monitor = threading.Thread(target=monitor, daemon=True)
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            while self._workers:
                self._reap()
