"""L4: Wikidata SPARQL ticker enrichment.

Re-implements both reference variants:

- the simple pass (``ticker_symbol_query.py:10-201``): three SPARQL queries
  per symbol — entity/labels/aliases/industries/countries/products (Q1),
  subsidiaries/owned entities with start/end qualifiers (Q2), CEOs/board
  members with term qualifiers (Q3) — zipped positionally into
  ``info/<dir>/<SYMBOL>_info.json``;
- the hardened pass (``ticker_symbol_query_rate_limit_protected.py``):
  retrying session (urllib3 ``Retry(total=5, backoff_factor=2,
  status_forcelist=[429,500,502,503,504])`` + browser UA, ref ``:11-31``),
  per-symbol attempt loop with 429-specific ``base·3^attempt`` escalation
  vs ``base·2^attempt`` otherwise plus jitter (ref ``:302-315``),
  inter-query 1-3 s sleeps, empty-result placeholder entries, progress
  ledger saved after every symbol with artifact-repair, and paced
  cool-downs every 3 / every 10 symbols (ref ``:417-427``).

Output text formats (``"Name (Start: …) (End: …)"`` with ``"| | |"``
separators) are load-bearing: ``match_keywords``-equivalent parsing in
``pipeline/matcher.py`` consumes them.  Clock/random/HTTP are injectable so
the whole ladder is testable offline.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Callable

from advanced_scrapper_tpu.config import EnrichConfig
from advanced_scrapper_tpu.storage.progress import ProgressLedger

SEP = "| | |"

# SPARQL property map (same entity graph the reference walks):
#   P414/P249  listed-on-exchange ticker   P452 industry     P17  country
#   P1056 products                         P355 subsidiaries P1830 owner-of
#   P169 CEO (+P580/P582 terms)            P3320 board member (+terms)


def build_queries(symbol: str) -> tuple[str, str, str]:
    sym = symbol.upper().replace("'", "")  # defensive: symbol goes into SPARQL
    ticker_clause = f"""
        ?id wdt:P414 ?exchange .
        ?id p:P414 ?exchangesub .
        ?exchangesub pq:P249 ?ticker . FILTER(UCASE(STR(?ticker)) = '{sym}') .
    """
    q1 = f"""
    SELECT ?ticker ?id
        (GROUP_CONCAT(DISTINCT ?idLabel;separator="{SEP}") AS ?idLabels)
        (GROUP_CONCAT(DISTINCT ?altLabel; separator = "{SEP}") AS ?aliases)
        (GROUP_CONCAT(DISTINCT ?industryLabel; separator = "{SEP}") AS ?industries)
        (GROUP_CONCAT(DISTINCT ?countryLabel; separator = "{SEP}") AS ?countries)
        (GROUP_CONCAT(DISTINCT ?productLabel; separator = "{SEP}") AS ?products)
    WHERE {{
        {{ {ticker_clause}
           OPTIONAL {{ ?id rdfs:label ?idLabel . FILTER (LANG(?idLabel) = "en") }} }}
        OPTIONAL {{ ?id skos:altLabel ?altLabel . FILTER (LANG(?altLabel) = "en") }}
        OPTIONAL {{ ?id wdt:P452 ?industry .
                    ?industry rdfs:label ?industryLabel .
                    FILTER (LANG(?industryLabel) = "en") }}
        OPTIONAL {{ ?id wdt:P17 ?country .
                    ?country rdfs:label ?countryLabel .
                    FILTER (LANG(?countryLabel) = "en") }}
        OPTIONAL {{ ?id wdt:P1056 ?product .
                    ?product rdfs:label ?productLabel .
                    FILTER (LANG(?productLabel) = "en") }}
        SERVICE wikibase:label {{ bd:serviceParam wikibase:language "[AUTO_LANGUAGE],en". }}
    }}
    GROUP BY ?ticker ?id
    """
    q2 = f"""
    SELECT ?ticker ?id
        (GROUP_CONCAT(DISTINCT ?idLabel;separator="{SEP}") AS ?idLabels)
        (GROUP_CONCAT(DISTINCT CONCAT(?subsidiaryLabel,
            IF(BOUND(?start_time), CONCAT(" (Start: ", STR(?start_time), ")"), ""),
            IF(BOUND(?end_time), CONCAT(" (End: ", STR(?end_time), ")"), "")
        );separator="{SEP}") AS ?subsidiaries)
        (GROUP_CONCAT(DISTINCT CONCAT(?ownerOfLabel,
            IF(BOUND(?start_time_owner), CONCAT(" (Start: ", STR(?start_time_owner), ")"), ""),
            IF(BOUND(?end_time_owner), CONCAT(" (End: ", STR(?end_time_owner), ")"), "")
        );separator="{SEP}") AS ?ownedEntities)
    WHERE {{
        {{ {ticker_clause}
           OPTIONAL {{ ?id rdfs:label ?idLabel . FILTER (LANG(?idLabel) = "en") }} }}
        OPTIONAL {{ ?id wdt:P355 ?subsidiary .
                    ?subsidiary rdfs:label ?subsidiaryLabel .
                    FILTER (LANG(?subsidiaryLabel) = "en")
                    OPTIONAL {{ ?id p:P355 [ps:P355 ?subsidiary; pq:P580 ?start_time; pq:P582 ?end_time] }} }}
        OPTIONAL {{ ?id wdt:P1830 ?ownerOf .
                    ?ownerOf rdfs:label ?ownerOfLabel .
                    FILTER (LANG(?ownerOfLabel) = "en")
                    OPTIONAL {{ ?id p:P1830 [ps:P1830 ?ownerOf; pq:P580 ?start_time_owner; pq:P582 ?end_time_owner] }} }}
        SERVICE wikibase:label {{ bd:serviceParam wikibase:language "[AUTO_LANGUAGE],en". }}
    }}
    GROUP BY ?ticker ?id
    """
    q3 = f"""
    SELECT ?ticker ?id
        (GROUP_CONCAT(DISTINCT CONCAT(?ceoLabel,
            IF(BOUND(?ceoStart), CONCAT(" (Start: ", STR(?ceoStart), ")"), ""),
            IF(BOUND(?ceoEnd), CONCAT(" (End: ", STR(?ceoEnd), ")"), "")
        );separator="{SEP}") AS ?ceosWithTerms)
        (GROUP_CONCAT(DISTINCT CONCAT(?boardMemberLabel,
            IF(BOUND(?boardMemberStart), CONCAT(" (Start: ", STR(?boardMemberStart), ")"), ""),
            IF(BOUND(?boardMemberEnd), CONCAT(" (End: ", STR(?boardMemberEnd), ")"), "")
        );separator="{SEP}") AS ?boardMembersWithTerms)
    WHERE {{
        {{ {ticker_clause} }}
        OPTIONAL {{ ?id p:P169 ?ceoStatement .
                    ?ceoStatement ps:P169 ?ceo .
                    ?ceo rdfs:label ?ceoLabel .
                    FILTER (LANG(?ceoLabel) = "en")
                    OPTIONAL {{ ?ceoStatement pq:P580 ?ceoStart }}
                    OPTIONAL {{ ?ceoStatement pq:P582 ?ceoEnd }} }}
        OPTIONAL {{ ?id p:P3320 ?boardMemberStatement .
                    ?boardMemberStatement ps:P3320 ?boardMember .
                    ?boardMember rdfs:label ?boardMemberLabel .
                    FILTER (LANG(?boardMemberLabel) = "en")
                    OPTIONAL {{ ?boardMemberStatement pq:P580 ?boardMemberStart }}
                    OPTIONAL {{ ?boardMemberStatement pq:P582 ?boardMemberEnd }} }}
        SERVICE wikibase:label {{ bd:serviceParam wikibase:language "[AUTO_LANGUAGE],en". }}
    }}
    GROUP BY ?ticker ?id
    """
    return q1, q2, q3


def _split(binding: dict, field: str) -> list[str]:
    value = binding.get(field, {}).get("value", "")
    if not value:
        return []
    return [part for part in value.split(SEP) if part.strip()]


def empty_entry(symbol: str) -> dict:
    return {
        "id_label": "",
        "ticker": symbol,
        "country": [],
        "industry": [],
        "aliases": [],
        "products": [],
        "subsidiaries": [],
        "owned_entities": [],
        "ceos": [],
        "board_members": [],
    }


def zip_results(data_1: dict, data_2: dict, data_3: dict, symbol: str) -> list[dict]:
    """Positionally zip the three result sets (hardened semantics: pad the
    shorter sets, drop empty strings, placeholder when nothing matched;
    ref protected ``:213-271``)."""
    b1 = data_1["results"]["bindings"]
    b2 = data_2["results"]["bindings"]
    b3 = data_3["results"]["bindings"]
    out = []
    for i in range(max(len(b1), len(b2), len(b3))):
        r1 = b1[i] if i < len(b1) else {}
        r2 = b2[i] if i < len(b2) else {}
        r3 = b3[i] if i < len(b3) else {}
        out.append(
            {
                "id_label": r1.get("idLabels", {}).get("value", ""),
                "ticker": r1.get("ticker", {}).get("value", symbol),
                "country": _split(r1, "countries"),
                "industry": _split(r1, "industries"),
                "aliases": _split(r1, "aliases"),
                "products": _split(r1, "products"),
                "subsidiaries": _split(r2, "subsidiaries"),
                "owned_entities": _split(r2, "ownedEntities"),
                "ceos": _split(r3, "ceosWithTerms"),
                "board_members": _split(r3, "boardMembersWithTerms"),
            }
        )
    if not out:
        out.append(empty_entry(symbol))
    return out


def create_session(hardened: bool = True):
    """Requests session: retry-hardened (ref protected ``:11-31``) or, for
    the simple flow, a bare session with no transport-level retries (the
    un-hardened script used plain ``requests.get``)."""
    import requests

    session = requests.Session()
    if not hardened:
        return session
    from requests.adapters import HTTPAdapter
    from urllib3.util.retry import Retry

    retry = Retry(
        total=5,
        backoff_factor=2,
        status_forcelist=[429, 500, 502, 503, 504],
        allowed_methods=["GET"],
    )
    adapter = HTTPAdapter(max_retries=retry)
    session.mount("https://", adapter)
    session.mount("http://", adapter)
    session.headers.update(
        {
            "User-Agent": (
                "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 "
                "(KHTML, like Gecko) Chrome/120.0.0.0 Safari/537.36"
            )
        }
    )
    return session


class EnrichClient:
    """Per-symbol query ladder with the hardened retry/backoff policy."""

    def __init__(
        self,
        cfg: EnrichConfig,
        *,
        session=None,
        sleep: Callable[[float], None] = time.sleep,
        rng: random.Random | None = None,
    ):
        self.cfg = cfg
        self.session = (
            session if session is not None else create_session(cfg.hardened)
        )
        self.sleep = sleep
        self.rng = rng or random.Random()

    def _get(self, query: str):
        return self.session.get(
            self.cfg.endpoint,
            params={"query": query, "format": "json"},
            timeout=(self.cfg.connect_timeout, self.cfg.read_timeout),
        )

    def query_symbol(self, symbol: str) -> bool:
        """Fetch + persist one symbol; True on success (ref protected :176-335).

        With ``cfg.hardened`` False this is the simple script's single pass
        — one attempt, no inter-query jitter, no politeness sleep, no
        backoff ladder (ref ``ticker_symbol_query.py``'s plain flow)."""
        q1, q2, q3 = build_queries(symbol)
        base = self.cfg.base_delay
        hardened = self.cfg.hardened
        attempts = self.cfg.max_retries if hardened else 1
        for attempt in range(attempts):
            try:
                r1 = self._get(q1)
                if hardened:
                    self.sleep(self.rng.uniform(1, 3))
                r2 = self._get(q2)
                if hardened:
                    self.sleep(self.rng.uniform(1, 3))
                r3 = self._get(q3)
                if r1.ok and r2.ok and r3.ok:
                    entries = zip_results(r1.json(), r2.json(), r3.json(), symbol)
                    os.makedirs(self.cfg.out_dir, exist_ok=True)
                    path = os.path.join(self.cfg.out_dir, f"{symbol}_info.json")
                    with open(path, "w", encoding="utf-8") as f:
                        json.dump(entries, f, indent=4, ensure_ascii=False)
                    if hardened:
                        self.sleep(self.rng.uniform(5, 10))  # politeness (ref :287)
                    return True
                # 429 escalates faster than other failures (ref :302-315)
                if any(r.status_code == 429 for r in (r1, r2, r3)):
                    if attempt < attempts - 1:
                        self.sleep(base * (3**attempt) + self.rng.uniform(10, 20))
                    else:
                        return False
                elif attempt < attempts - 1:
                    self.sleep(base * (2**attempt) + self.rng.uniform(2, 8))
            except Exception:
                if attempt < attempts - 1:
                    self.sleep(base * (2**attempt) + self.rng.uniform(5, 15))
                else:
                    return False
        return False

    def artifact_path(self, symbol: str) -> str:
        return os.path.join(self.cfg.out_dir, f"{symbol}_info.json")


def run_enrich(
    cfg: EnrichConfig,
    *,
    session=None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    symbols: list[str] | None = None,
) -> int:
    """CLI entry: enrich every symbol with ledger resume + paced cool-downs."""
    import csv

    rng = rng or random.Random()
    client = EnrichClient(cfg, session=session, sleep=sleep, rng=rng)

    if symbols is None:
        if not os.path.exists(cfg.symbols_csv):
            print(f"Symbols CSV '{cfg.symbols_csv}' not found.")
            return 1
        with open(cfg.symbols_csv, newline="", encoding="utf-8") as f:
            symbols = [row["Symbol"] for row in csv.DictReader(f) if row.get("Symbol")]

    ledger = ProgressLedger(cfg.progress_file) if cfg.hardened else None
    done = 0
    for idx, symbol in enumerate(symbols):
        if ledger is not None and ledger.should_skip(
            symbol, lambda s=symbol: os.path.exists(client.artifact_path(s))
        ):
            continue
        ok = client.query_symbol(symbol)
        if ledger is not None:
            (ledger.mark_processed if ok else ledger.mark_failed)(symbol)
        done += 1
        if cfg.hardened:
            # paced cool-downs (ref protected :417-427)
            if done % 10 == 0:
                sleep(rng.uniform(*cfg.cooldown_every10))
            elif done % 3 == 0:
                sleep(rng.uniform(*cfg.cooldown_every3))
    print(f"Enrichment finished: {done} symbols attempted.")
    return 0


def run_crypto_enrich(
    cfg: EnrichConfig,
    *,
    symbols: list[str] | None = None,
    **kw,
) -> int:
    """Crypto-symbol enrichment: the same Wikidata Q1/Q2/Q3 flow routed to
    the crypto artifact tree.

    The reference keeps ``info/crypto/*.json`` beside ``info/ticker/*.json``
    (SURVEY.md §L4 artifact map; the commented legacy flow at
    ``ticker_symbol_query.py:205-265`` wrote ``info/<symbol>_info.json``).
    Here the crypto list rides the hardened client unchanged — only the
    symbol source (``crypto_symbols_csv``), output dir, and progress ledger
    are swapped, so retries/cool-downs/resume behave identically to the
    ticker flow.
    """
    import dataclasses

    crypto_cfg = dataclasses.replace(
        cfg,
        symbols_csv=cfg.crypto_symbols_csv,
        out_dir=cfg.crypto_out_dir,
        progress_file=cfg.crypto_progress_file,
    )
    os.makedirs(crypto_cfg.out_dir, exist_ok=True)
    return run_enrich(crypto_cfg, symbols=symbols, **kw)
