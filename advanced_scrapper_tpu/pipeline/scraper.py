"""Constant-rate acquisition engine (L2).

Re-implements the production engine's semantics
(``constant_rate_scrapper.py:115-493``) with the races designed out
(SURVEY.md §5.2):

- **admission control at the admit stage**, not the workers: one URL
  enters the runtime-owned ``urls`` edge every ``1/rate`` seconds (ref
  ``:207-220``).  The whole fixed mode is a stage graph
  (``runtime.StageGraph``): admit → urls → fetch×N → results, with the
  scheduler owning queues, backpressure, shutdown ordering and the
  crash drain-snapshot;
- **worker pool** of N fetch-stage workers, each owning its transport via
  the stage's ``worker_init``/``worker_close`` bracket (the ref's
  per-thread Firefox, ``:136``);
- **rate-limit circuit breaker**: the extractor's ``rate_limit_reached``
  sentinel or a network fingerprint (``contentEncodingError`` /
  ``about:neterror``, ref ``:190-193``) trips a global pause for
  ``rate_limit_wait`` seconds.  The ref mutates an unlocked global ``pause``
  read by three threads; here :class:`PauseController` owns a deadline
  behind a lock;
- **single-writer CSVs**: only the result loop touches the success/failed
  files (the ref locks per-file; we remove the shared mutation instead),
  flush-per-row so the checkpoint is always current;
- **resume**: the work list is anti-joined against urls already present in
  the success/failed CSVs (ref ``:316-356``) — failures are first-class
  data and are not retried;
- a URL consumed by a rate-limited fetch is *not* written anywhere, so a
  later resume retries it (ref behaviour, ``:160-164``).

The optional ``on_success`` hook is the CPU→TPU seam: ``run_scraper`` wires
it to ``extractors.tpu_batch.TpuBatchBackend.submit`` so scraped articles
stream into device batches asynchronously (north star).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence

from bs4 import BeautifulSoup

from advanced_scrapper_tpu.config import ScraperConfig
from advanced_scrapper_tpu.obs.console import ConsoleMux
from advanced_scrapper_tpu.obs.stats import StatsTracker
from advanced_scrapper_tpu.runtime import DONE, StageGraph

# the deadline-based global pause moved to the runtime (every graph can
# honour it, not just the scraper); re-exported here because this module
# has always been its import site
from advanced_scrapper_tpu.runtime.pause import PauseGate as PauseController  # noqa: F401,E501
from advanced_scrapper_tpu.storage.csvio import AppendCsv, count_rows, scraped_url_set

# canonical home is the extractor boundary (the schema is the plugin
# contract's output, and net/ consumes it too); re-exported here because
# this module has always been its import site
from advanced_scrapper_tpu.extractors import (  # noqa: F401
    FAILED_FIELDS,
    SUCCESS_FIELDS,
)

_RATE_LIMIT_FINGERPRINTS = (
    "contentEncodingError",  # Firefox/geckodriver (ref :190)
    "about:neterror",        # Firefox/geckodriver (ref :190)
    "net::ERR_",             # Chrome/CDP network errors (stealth-chrome
    #                          transport: net::ERR_CONNECTION_RESET etc. —
    #                          without these the circuit breaker is blind
    #                          on the Chrome substrate)
    "ERR_HTTP2_PROTOCOL_ERROR",
)


@dataclass
class ScrapeSummary:
    total_urls: int = 0
    already_scraped: int = 0
    attempted: int = 0
    succeeded: int = 0
    failed: int = 0
    rate_limited_skipped: int = 0  # consumed by a sentinel page; retried on resume
    rate_limit_trips: int = 0
    errors: list = field(default_factory=list)


class ScraperEngine:
    def __init__(
        self,
        cfg: ScraperConfig,
        extractor: Callable,
        transport_factory: Callable[[], object],
        *,
        console: ConsoleMux | None = None,
        on_success: Callable[[dict], None] | None = None,
        sleep=time.sleep,
    ):
        self.cfg = cfg
        self.extractor = extractor
        self.transport_factory = transport_factory
        # An unstarted mux would buffer one string per URL for the whole run;
        # when the engine owns the console it runs (and stops) the consumer.
        self._owns_console = console is None
        self.console = console or ConsoleMux()
        self.on_success = on_success
        self.sleep = sleep
        self.stats = StatsTracker(window=cfg.stats_time_window)
        self.pause = PauseController()
        self._stop = threading.Event()
        self._bridge_stats()

    _seq_lock = threading.Lock()
    _seq = 0

    def _bridge_stats(self) -> None:
        """Bridge the engine-local :class:`StatsTracker` (and the pause
        controller) into the process registry as scrape-time callback
        gauges — the 10 Hz console line and ``/metrics`` now read the same
        tracker.  Weakref'd on the engine: a finished run unregisters
        itself; no hot-path cost (workers keep calling the tracker
        directly)."""
        from advanced_scrapper_tpu.obs import telemetry

        with ScraperEngine._seq_lock:
            eid = str(ScraperEngine._seq)
            ScraperEngine._seq += 1
        telemetry.gauge_fn(
            "astpu_scraper_fetch_success",
            lambda e: e.stats.get_cumulative_stats()[0],
            owner=self,
            help="cumulative successful fetches this run",
            engine=eid,
        )
        telemetry.gauge_fn(
            "astpu_scraper_fetch_fail",
            lambda e: e.stats.get_cumulative_stats()[1],
            owner=self,
            help="cumulative failed fetches this run",
            engine=eid,
        )
        telemetry.gauge_fn(
            "astpu_scraper_request_rate",
            lambda e: e.stats.get_actual_rate(),
            owner=self,
            help="requests/s over the stats window",
            engine=eid,
        )
        telemetry.gauge_fn(
            "astpu_scraper_pause_remaining_seconds",
            lambda e: e.pause.remaining(),
            owner=self,
            help="rate-limit circuit-breaker countdown (0 = not paused)",
            engine=eid,
        )

    # -- worker ------------------------------------------------------------

    def _classify(self, url: str, html: str):
        soup = BeautifulSoup(html, "html.parser")
        data = self.extractor(soup)
        if "rate_limit_reached" in str(data.get("error", "")).lower():
            # carry the url so the result loop can account for it; the url is
            # still written nowhere (resume retries it, ref :160-164)
            return ("rate_limit", {"url": url})
        if not data.get("title", ""):
            return ("failed", {"url": url, "error": "Title is empty"})
        data["url"] = url
        return ("success", data)

    def _fetch_one(self, transport, url: str) -> list[tuple[str, object]]:
        """Fetch + classify one url → result events (usually one; a
        fingerprinted network failure emits its failed row AND the
        rate-limit signal, like the reference).  Decisions, console lines,
        stats and circuit-breaker trips are identical for the fixed-mode
        graph stage and the elastic worker bodies — both call this."""
        try:
            html = transport.fetch(url)
            kind, payload = self._classify(url, html)
            if kind == "rate_limit":
                self.console.failure("!!!RATE LIMIT DETECTED!!!")
                self.pause.trigger(self.cfg.rate_limit_wait)
                return [("rate_limit", payload)]
            if kind == "failed":
                self.console.failure(f"FAIL {url} : {payload['error']}")
                self.stats.record_fail()
                return [("failed", payload)]
            self.console.success(f"SUCCESS: {url}")
            self.stats.record_success()
            return [("success", payload)]
        except Exception as e:
            msg = str(e)
            self.console.failure(f"FAIL {url} : {msg}")
            self.stats.record_fail()
            out: list[tuple[str, object]] = [("failed", {"url": url, "error": msg})]
            if any(fp in msg for fp in _RATE_LIMIT_FINGERPRINTS):
                self.console.failure(
                    "!!!RATE LIMIT DETECTED (network fingerprint)!!!"
                )
                self.pause.trigger(self.cfg.rate_limit_wait)
                out.append(("rate_limit", None))
            return out

    def _worker(
        self,
        url_q,
        result_q,
        worker_stop: threading.Event | None = None,
    ) -> None:
        """Elastic-mode worker body (driven by :class:`ElasticWorkerPool`,
        which owns thread count): the queues are runtime Edges speaking the
        ``queue.Queue`` surface; fixed mode runs the same fetch logic as a
        graph stage instead."""

        def stopped() -> bool:
            return self._stop.is_set() or (
                worker_stop is not None and worker_stop.is_set()
            )

        try:
            transport = self.transport_factory()
        except Exception as e:
            self.console.failure(f"Failed to start transport: {e}")
            self._stop.set()
            return
        try:
            while not stopped():
                try:
                    url = url_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                # honour the circuit breaker at the worker too: in elastic
                # modes there is no feeder to gate admission, so this is the
                # only place the pause can take effect
                self.pause.wait(sleep=self.sleep, should_stop=stopped)
                for item in self._fetch_one(transport, url):
                    result_q.put(item)
                url_q.task_done()
        finally:
            try:
                transport.close()
            except Exception:
                pass

    # -- stats line --------------------------------------------------------

    def _stats_line(self, initial_total: int, already: int) -> str:
        rate = self.stats.get_actual_rate()
        s, f = self.stats.get_stats()
        cs, cf = self.stats.get_cumulative_stats()
        total = cs + cf + already
        progress = (total / initial_total * 100) if initial_total else 0.0
        line = (
            f"Threads: {self.cfg.max_threads} | Requests: {rate:.2f}/s | "
            f"Last {int(self.cfg.stats_time_window)} s: {s} Success, {f} Fail | "
            f"Count: {total} | Progress: {progress:.4f}%"
        )  # format ref :236-242
        # Surface the circuit-break countdown so an operator can tell a
        # rate-limit pause from a stall (ref :244-249 renders this as a
        # per-second "resuming in N s" ticker).
        pause_left = self.pause.remaining()
        if pause_left > 0:
            line += f" | PAUSED: rate limit, resuming in {pause_left:.0f} s"
        return line

    # -- run ---------------------------------------------------------------

    def run(
        self,
        urls: Sequence[str],
        success_csv: str,
        failed_csv: str,
        *,
        initial_total: int | None = None,
        already_scraped: int = 0,
        show_stats: bool = False,
        mode: str = "fixed",
    ) -> ScrapeSummary:
        summary = ScrapeSummary(
            total_urls=len(urls), already_scraped=already_scraped
        )
        if self._owns_console and not self.console.running:
            self.console.start()
        initial_total = initial_total or len(urls)
        # ONE scheduler owns both queues: the graph's edges replace the
        # bespoke queue.Queue pair (elastic modes ride the same edges via
        # their queue-compat surface; the runtime's depth/stall telemetry
        # and the crash drain-snapshot cover both modes for free)
        # no graph-level pause gate on purpose: the fetch fn waits on
        # self.pause ITSELF so the engine's injectable sleep applies (the
        # runtime's pausable= path uses real time.sleep)
        graph = StageGraph("scrape")
        url_q = graph.edge("urls")       # unbounded: elastic modes pre-fill
        result_q = graph.edge("results")

        pool = None
        if mode == "fixed":
            # production design: fixed fetch pool + rate-paced admit stage
            # (ref C1).  admission control at the admit stage, not the
            # workers: one URL enters the edge every 1/rate seconds.
            urls_iter = iter(urls)
            interval = 1.0 / self.cfg.desired_request_rate
            first = [True]

            def admit():
                if self._stop.is_set():
                    return DONE
                if first[0]:
                    first[0] = False
                else:
                    self.sleep(interval)
                self.pause.wait(sleep=self.sleep, should_stop=self._stop.is_set)
                try:
                    return next(urls_iter)
                except StopIteration:
                    return DONE

            def init_transport():
                try:
                    return self.transport_factory()
                except Exception as e:
                    self.console.failure(f"Failed to start transport: {e}")
                    self._stop.set()
                    raise

            def fetch(url, transport):
                # honour the circuit breaker at the worker too (the pause
                # must gate in-queue urls, not just admission), with the
                # engine's injected sleep so tests stay fast
                self.pause.wait(sleep=self.sleep, should_stop=self._stop.is_set)
                return self._fetch_one(transport, url)

            graph.stage("admit", source=admit, out_edge=url_q)
            graph.stage(
                "fetch",
                fn=fetch,
                in_edge=url_q,
                out_edge=result_q,
                workers=self.cfg.max_threads,
                worker_init=init_transport,
                worker_close=lambda t: t.close(),
                fan_out=True,
            )
        else:
            # elastic designs: pre-filled queue, controller-driven pool size
            # (ref experiental/local_dynamic.py / local_pid.py)
            from advanced_scrapper_tpu.pipeline.controllers import (
                ElasticWorkerPool,
                PController,
                PIDController,
                PoolLimits,
            )

            for u in urls:
                url_q.put(u)
            if mode == "elastic-p":
                controller = PController(self.cfg.desired_request_rate)
                interval = 0.5  # ref local_dynamic.py:233
            elif mode == "elastic-pid":
                controller = PIDController(self.cfg.desired_request_rate)
                interval = 0.8  # ref local_pid.py:279
            else:
                raise ValueError(f"unknown mode '{mode}'")
            pool = ElasticWorkerPool(
                controller,
                self.stats,
                lambda ev: self._worker(url_q, result_q, ev),
                limits=PoolLimits(1, self.cfg.max_threads),
                interval=interval,
                sleep=self.sleep,
            ).start()
        # started in BOTH modes: the elastic graph has no stages (the
        # controller owns the workers) but starting it registers the run
        # — and its edges — with the crash-snapshot plane
        graph.start()

        stats_stop = threading.Event()
        if show_stats:
            def stats_loop():
                while not stats_stop.is_set():
                    self.console.stats(self._stats_line(initial_total, already_scraped))
                    self.sleep(0.1)

            threading.Thread(target=stats_loop, daemon=True).start()

        try:
            with AppendCsv(success_csv, SUCCESS_FIELDS) as ok_csv, AppendCsv(
                failed_csv, FAILED_FIELDS
            ) as bad_csv:
                processed = 0
                while processed < len(urls):
                    try:
                        kind, data = result_q.get(timeout=self.cfg.result_timeout)
                    except queue.Empty:
                        # a failed graph closes the results edge, which
                        # reads as an IMMEDIATE Empty — report the real
                        # exception the runtime captured, not a phantom
                        # timeout nobody can debug
                        if graph.error is not None:
                            summary.errors.append(
                                f"workers died: {graph.error!r}"
                            )
                        else:
                            summary.errors.append("result timeout")
                        break
                    if kind == "success":
                        ok_csv.write_row(data)  # write_row fills missing fields
                        summary.succeeded += 1
                        processed += 1
                        if self.on_success is not None:
                            try:
                                self.on_success(dict(data))
                            except Exception as e:
                                summary.errors.append(f"on_success: {e}")
                    elif kind == "failed":
                        bad_csv.write_row(data)
                        summary.failed += 1
                        processed += 1
                    elif kind == "rate_limit":
                        # Sentinel-path events carry the consumed url: count it so
                        # the loop terminates without stalling on result_timeout.
                        # Fingerprint-path events (data None) already produced a
                        # failed row and must not double-count.
                        if data is not None:
                            summary.rate_limited_skipped += 1
                            processed += 1
                        # Wait out the pause here too (ref :463-468) — otherwise
                        # the result timeout below would fire mid-pause and abort
                        # the run.  The pause controller is the single authority.
                        self.console.event(
                            f"Rate limit: pausing {self.pause.remaining():.0f} s"
                        )
                        self.pause.wait(sleep=self.sleep, should_stop=self._stop.is_set)
                        self.console.event("Resuming scraping.")
        finally:
            # always tear the fleet down — a CSV write failing with EIO
            # (chaos substrate, disk full) must not strand live worker
            # threads behind the propagating exception.  graph.stop()
            # closes every edge (waking blocked puts/pops); join bounds
            # the total wait like the per-thread joins it replaces.
            summary.attempted = summary.succeeded + summary.failed
            summary.rate_limit_trips = self.pause.trips
            self._stop.set()
            stats_stop.set()
            if pool is not None:
                pool.stop()
            graph.stop()
            graph.join(timeout=10, raise_error=False)
            if self._owns_console:
                self.console.stop()
            self.console.drain()
        return summary


def run_scraper(
    cfg: ScraperConfig,
    *,
    transport_factory: Callable[[], object] | None = None,
    urls: Iterable[str] | None = None,
    with_tpu_backend: bool = True,
    show_stats: bool = True,
) -> int:
    """CLI entry: resume-aware scrape of ``cfg.input_csv``.

    Mirrors ``constant_rate_scrapper.main()`` (``:289-493``): dynamic
    extractor import, CSV resume anti-join, then the engine; optionally
    streams successes into the TPU dedup backend (north star).
    """
    import os

    from advanced_scrapper_tpu.extractors import load_extractor

    extractor = load_extractor(cfg.website)

    success_csv = os.path.join(cfg.out_dir, f"success_articles_{cfg.website}.csv")
    failed_csv = os.path.join(cfg.out_dir, f"failed_articles_{cfg.website}.csv")

    if urls is None:
        from advanced_scrapper_tpu.storage.csvio import read_url_column

        if not os.path.exists(cfg.input_csv):
            print(f"Input CSV file '{cfg.input_csv}' not found.")
            return 1
        urls = read_url_column(cfg.input_csv)
    all_urls = [str(u) for u in urls]
    initial_total = len(all_urls)

    scraped = scraped_url_set(success_csv, failed_csv)
    already = count_rows(success_csv) + count_rows(failed_csv)
    todo = [u for u in all_urls if u not in scraped]
    print(f"Total URLs in CSV: {initial_total}")
    print(f"Already scraped (Success + Fails): {already}")
    print(f"Remaining URLs to scrape: {len(todo)}")

    if transport_factory is None:
        from advanced_scrapper_tpu.net.transport import make_transport

        transport_factory = lambda: make_transport(  # noqa: E731
            cfg.transport,
            page_load_timeout=cfg.page_load_timeout,
            ready_state_timeout=cfg.ready_state_timeout,
        )

    on_success = None
    backend = None
    ann_csv = None
    if with_tpu_backend:
        from advanced_scrapper_tpu.config import DedupConfig, from_env
        from advanced_scrapper_tpu.extractors.tpu_batch import TpuBatchBackend
        from advanced_scrapper_tpu.storage.csvio import AppendCsv as _Csv

        ann_csv = _Csv(
            os.path.join(cfg.out_dir, f"dedup_annotations_{cfg.website}.csv"),
            ["url", "dup_of", "near_dup_of"],
        )
        # from_env: the ASTPU_DEDUP_* knobs (stream_index=persist, the
        # checkpoint cadence, index geometry) reach the production entry
        dedup_cfg = from_env(DedupConfig, "dedup")
        index_dir = dedup_cfg.index_dir or os.path.join(
            cfg.out_dir, f"stream_index_{cfg.website}"
        )
        backend = TpuBatchBackend(
            dedup_cfg,
            sink=lambda rec: ann_csv.write_row(
                {
                    "url": rec.get("url", ""),
                    "dup_of": rec.get("dup_of") or "",
                    "near_dup_of": rec.get("near_dup_of") or "",
                }
            ),
            index_dir=index_dir,
        )
        if dedup_cfg.stream_index == "persist" and dedup_cfg.index_fleet:
            # remote fleet: announce the topology (the per-shard health is
            # live on /metrics, astpu_fleet_*; spill journals land under
            # the local index dir)
            print(
                f"Stream index: remote fleet "
                f"[{dedup_cfg.index_fleet}], spill at {index_dir}/spill"
            )
        # the fifth resume artifact: without the stream index a restarted
        # run re-admits near-dups of everything already annotated; a torn
        # checkpoint (pre-hardening crash) is quarantined and ignored.  In
        # persist mode the npz path is the LEGACY artifact, auto-imported
        # once into the durable index (MIGRATION.md).
        index_ckpt = os.path.join(cfg.out_dir, f"stream_index_{cfg.website}.npz")
        backend.load_index_if_valid(index_ckpt)

        # checkpoint cadence (DedupConfig.ckpt_every_batches — previously
        # the index persisted only at run end): every N processed device
        # batches the stream index checkpoints, so a crash loses at most N
        # batches of dedup memory, never the whole run's.  0 disables the
        # periodic checkpoint (end-of-run only — the right setting for
        # huge exact-mode corpora, where each checkpoint is a full npz
        # rewrite; persist mode checkpoints are O(new postings))
        every = dedup_cfg.ckpt_every_batches

        def on_success(rec, _backend=backend, _every=every, _ckpt=index_ckpt):
            if (
                _backend.submit(rec)
                and _every > 0
                and _backend.stats.batches % _every == 0
            ):
                _backend.checkpoint(_ckpt)

    console = ConsoleMux().start()
    engine = ScraperEngine(
        cfg,
        extractor,
        transport_factory,
        console=console,
        on_success=on_success,
    )
    try:
        summary = engine.run(
            todo,
            success_csv,
            failed_csv,
            initial_total=initial_total,
            already_scraped=already,
            show_stats=show_stats,
        )
    finally:
        # nested so a failing flush/save (disk full, ...) can neither mask
        # the run's own exception with a half-cleaned console nor skip
        # closing the annotation CSV
        try:
            if backend is not None:
                backend.flush()
                backend.save_index(index_ckpt)
                backend.close()
        finally:
            try:
                if ann_csv is not None:
                    ann_csv.close()
            finally:
                console.stop()
    print(
        f"\nScraping completed: {summary.succeeded} success, "
        f"{summary.failed} failed, {summary.rate_limited_skipped} rate-limited, "
        f"{summary.rate_limit_trips} rate-limit trips."
    )
    return 0
