"""Live topic poller: discover new article links on a rolling basis.

Re-implements the reference's live-news loops (``experiental/04_crypto_1.py``
/ ``09_btc_links.py`` + the article side of ``05``/``10``):

- poll a topic page (default the crypto feed) every ``interval`` seconds;
- keep links passing the reference's filter — contains ``/news/`` AND
  ``.html`` AND ``https:`` (``04:75``);
- insert-or-ignore into the link store (``is_scraped`` flag resume);
- optionally drain unscraped links through an extractor into the article
  store, re-queueing whatever fails so the loop retries it forever
  (``10:248-268``).

Transport/clock/sleep are injectable; ``max_iterations`` makes the infinite
reference loop testable and cron-able.

Two reference behaviours restored in round 2 (VERDICT items 3 and 9):

- **mirror CSV** — ``04_crypto_1.py:76-80`` writes every new link to
  Postgres *and* a CSV; ``poll_links(mirror_csv=...)`` does the same.
- **scroll-to-load** — ``04:57-63`` scrolls the topic page to force lazy
  loading before collecting links.  ``poll_links(scroll=True)`` uses the
  transport's ``fetch_scrolled`` when it has one (``SeleniumTransport``
  scrolls until the page height stabilises); plain-HTTP transports have no
  scroll analogue — discovery coverage is then the first page only, and
  the fallback is logged once so the difference is visible.
"""

from __future__ import annotations

import csv
import os
import time
from datetime import datetime, timezone
from typing import Callable

from bs4 import BeautifulSoup

from advanced_scrapper_tpu.storage.stores import ArticleStore, LinkStore

DEFAULT_TOPIC_URL = "https://finance.yahoo.com/topic/crypto/"


def extract_topic_links(html: str) -> list[str]:
    """All hrefs passing the reference link filter (ref 04:74-75)."""
    soup = BeautifulSoup(html, "html.parser")
    out = []
    for a in soup.find_all("a", href=True):
        link = a["href"]
        if "/news/" in link and ".html" in link and "https:" in link:
            out.append(link)
    return out


def _mirror_new_links(path: str, urls: list[str], now: float) -> None:
    """Append new links to the mirror CSV (ref 04:76-80 writes url + time)."""
    utc = datetime.fromtimestamp(now, timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
    header = not os.path.exists(path) or os.path.getsize(path) == 0
    with open(path, "a", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        if header:
            w.writerow(["url", "first_seen_utc"])
        for u in urls:
            w.writerow([u, utc])
        f.flush()


def poll_links(
    store: LinkStore,
    transport,
    *,
    topic_url: str = DEFAULT_TOPIC_URL,
    interval: float = 3.0,       # ref 04 polls every 3 s
    max_iterations: int | None = None,
    sleep: Callable[[float], None] = time.sleep,
    on_new: Callable[[list[str]], None] | None = None,
    mirror_csv: str | None = None,
    scroll: bool = False,
) -> int:
    """Poll loop; returns total NEW links discovered."""
    total_new = 0
    i = 0
    scroll_warned = False
    while max_iterations is None or i < max_iterations:
        i += 1
        try:
            if scroll and hasattr(transport, "fetch_scrolled"):
                html = transport.fetch_scrolled(topic_url)
            else:
                if scroll and not scroll_warned:
                    scroll_warned = True
                    print(
                        f"poll: transport {type(transport).__name__} cannot "
                        "scroll; lazy-loaded links beyond the first page "
                        "will not be discovered"
                    )
                html = transport.fetch(topic_url)
            links = extract_topic_links(html)
            now = time.time()
            fresh = store.add_links(links, now=now)
            total_new += len(fresh)
            if fresh and mirror_csv is not None:
                _mirror_new_links(mirror_csv, fresh, now)
            if fresh and on_new is not None:
                on_new(fresh)
        except Exception as e:
            print(f"poll error: {e}")
        if max_iterations is None or i < max_iterations:
            sleep(interval)
    return total_new


def drain_unscraped(
    link_store: LinkStore,
    article_store: ArticleStore,
    transport,
    extractor: Callable,
    *,
    max_rounds: int = 1,
    sleep: Callable[[float], None] = time.sleep,
    round_interval: float = 15.0,  # ref 10 re-queues unscraped every pass
) -> int:
    """Scrape every unscraped link into the article store; failed links stay
    flagged unscraped and are retried next round (ref 10:248-268)."""
    stored = 0
    for r in range(max_rounds):
        todo = link_store.unscraped()
        if not todo:
            break
        for url in todo:
            try:
                html = transport.fetch(url)
                data = extractor(BeautifulSoup(html, "html.parser"))
                if not data.get("title"):
                    continue  # stays unscraped → retried
                article_store.store(url, data)
                stored += 1
            except Exception as e:
                print(f"drain error for {url}: {e}")
        if r < max_rounds - 1 and link_store.unscraped():
            sleep(round_interval)
    return stored
