"""Dedup engines — the TPU rerouting of the reference's dedup steps.

- :class:`NearDupEngine` — MinHash(k=5, 128-perm) + 16-band LSH near-dup
  clustering (the north-star workload; no analogue in the reference, which
  only ever does exact dedup).
- :class:`ExactDedup` — byte-identical replacement for pandas
  ``drop_duplicates(subset=['url'], keep='first')``
  (``yahoo_links_selenium.py:79,174``): 128-bit device hashing proposes
  groups, the host confirms true string equality inside each group, so the
  surviving row set is *provably* identical to the pandas path.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import MinHashParams, make_params
from advanced_scrapper_tpu.core.tokenizer import (
    bucket_len,
    bucket_widths,
    encode_blocks,
    to_bytes,
)
from advanced_scrapper_tpu.ops.exact import ExactHasher
from advanced_scrapper_tpu.ops.lsh import (
    borderline_edge_mask,
    candidate_keys,
    duplicate_rep_bands,
    fine_edge_thresholds,
    resolve_rep_bands,
    resolve_rep_bands_from_ok,
)
from advanced_scrapper_tpu.ops.minhash import resolve_signature_fn


#: the DECLARED hook edge for ROADMAP item 2's candidate-verification
#: (rerank) tier: in the dedup stage graph (encode → h2d → kernel →
#: candidates → resolve) this names the edge between candidate generation
#: and union-find resolution.  A :attr:`NearDupEngine.rerank_hook`
#: callable ``(raw, sigs, rep_bands, valid) -> rep_bands`` slots in here —
#: BOTH resolution paths (async/estimator-only and the certified one-shot)
#: route their candidate matrix through it before resolving, so a
#: device-batched exact-Jaccard rerank tier becomes a graph edge, not a
#: bespoke rewrite of either path.
RERANK_HOOK_EDGE = "dedup.candidates->dedup.resolve"


def _jump_rounds(n: int) -> int:
    r = 1
    while (1 << r) < n:
        r += 1
    return r


def resolve_put_workers(cfg: DedupConfig) -> int:
    """Effective H2D put-thread count: ``cfg.put_workers``, with 0 meaning
    the transport default (``core.mesh.auto_h2d_workers`` — 4 on the
    serializing axon tunnel, 1 on local backends).  Lives in the engine so
    production configs and bench defaults resolve identically."""
    if cfg.put_workers:
        return cfg.put_workers
    from advanced_scrapper_tpu.core.mesh import auto_h2d_workers

    return auto_h2d_workers()


class NearDupEngine:
    """Batch near-duplicate detector.

    Long texts are split into overlapping blocks (`core.tokenizer.encode_blocks`)
    so device shapes stay fixed; block signatures are exactly min-combined per
    article. Block batches are padded to a fixed size to avoid recompilation.
    """

    def __init__(self, cfg: DedupConfig | None = None, params: MinHashParams | None = None):
        self.cfg = cfg or DedupConfig()
        self.params = params or make_params(
            num_perm=self.cfg.num_perm,
            num_bands=self.cfg.num_bands,
            shingle_k=self.cfg.shingle_k,
            seed=self.cfg.seed,
        )
        # compiled fused-step cache for dedup_reps_sharded, keyed on
        # (mesh, article bucket, block_len) — meshes are hashable
        self._sharded_steps: dict = {}
        #: the rerank tier's slot on :data:`RERANK_HOOK_EDGE` — when set,
        #: every resolution path passes its candidate matrix through it
        #: before union-find (None = pass-through)
        self.rerank_hook = None
        self._instrument()

    def _instrument(self) -> None:
        """Telemetry handles (no-ops when disabled) + the production home
        of the once-orphaned ``StepTimer``: every device block-batch
        dispatch lands in it, so ``step_summary()`` answers "what is the
        per-dispatch latency right now" on a live engine."""
        from advanced_scrapper_tpu.obs import telemetry
        from advanced_scrapper_tpu.obs.profiler import StepTimer

        self.step_timer = StepTimer(
            histogram=telemetry.histogram(
                "astpu_dedup_step_seconds", "device block-batch dispatch latency"
            )
        )
        self._m_batches = telemetry.counter(
            "astpu_dedup_batches_total", "device block batches dispatched"
        )
        self._m_docs = {
            regime: telemetry.counter(
                "astpu_dedup_docs_total",
                "documents entering dedup",
                regime=regime,
            )
            for regime in ("oneshot", "async", "sharded")
        }
        self._m_dups = {
            regime: telemetry.counter(
                "astpu_dedup_dups_total",
                "documents resolved as near-duplicates",
                regime=regime,
            )
            for regime in ("oneshot", "sharded")
        }
        self._m_ratio = {
            regime: telemetry.gauge(
                "astpu_dedup_ratio",
                "last corpus' duplicate fraction",
                regime=regime,
            )
            for regime in ("oneshot", "sharded")
        }
        self._m_cand = telemetry.counter(
            "astpu_dedup_candidate_pairs_total",
            "LSH candidate (row, band) hits examined by the certified "
            "one-shot resolution (async/sharded never sync candidates)",
        )
        self._m_borderline = telemetry.counter(
            "astpu_dedup_borderline_edges_total",
            "estimator-fragile edges flagged for exact confirmation",
        )
        self._m_exact_checks = telemetry.counter(
            "astpu_dedup_exact_checks_total",
            "exact shingle-set Jaccard confirmations run",
        )

    def step_summary(self) -> dict:
        """Rolling per-dispatch latency/throughput (``StepTimer.summary``)."""
        return self.step_timer.summary()

    def _count_result(self, regime: str, n: int, reps: np.ndarray) -> None:
        """Host-side dedup-ratio accounting — only for paths that already
        synced ``reps`` (the async path never syncs; it counts docs only).
        The numpy reduction is metric-only work, so it is skipped entirely
        when telemetry handed out no-op handles (the disabled cost model)."""
        from advanced_scrapper_tpu.obs.telemetry import NOOP

        if n == 0 or self._m_dups[regime] is NOOP:
            return
        dups = int((reps[:n] != np.arange(n)).sum())
        self._m_dups[regime].inc(dups)
        self._m_ratio[regime].set(dups / n)

    def signatures(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """uint32[N, num_perm] MinHash signatures (blockwise, batched).

        With ``cfg.backend == "oph"`` block signatures are the *raw* OPH
        form (empty bins ``U32_MAX``) so the per-article segment-min combine
        stays exact; densification runs once after the combine (see
        ``ops/oph.py`` for why that order is load-bearing).
        """
        if len(texts) == 0:
            return np.zeros((0, self.params.num_perm), np.uint32)
        from advanced_scrapper_tpu.obs import stages, trace

        tid = trace.new_trace_id()
        sigs = self._signatures_device(texts, trace_id=tid)
        with stages.timed("kernel"), trace.span(
            "dedup.readback", trace=tid, docs=len(texts)
        ):  # readback sync: the device drains here
            return np.asarray(sigs)[: len(texts)]

    def _signatures_device(self, texts: Sequence[str | bytes], trace_id=None):
        """Device ``uint32[bucket_len(N), num_perm]`` combined signatures.

        The ragged corpus is grouped by power-of-two *width buckets* (a doc
        of 700 B rides a 1024-wide row, not a block_len-wide one) and docs
        longer than ``cfg.block_len`` split blockwise; every group folds
        into one running per-article minimum on device.  Two properties are
        load-bearing for throughput on an H2D-constrained link (the ragged
        regime is transfer-bound, not compute-bound — DESIGN.md §5):

        - bucketing cuts padded bytes on realistic length mixes vs
          one-width encoding, and padding that remains is zeros (cheap for
          a compressing transport);
        - every batch is explicitly ``jax.device_put`` (async) BEFORE its
          kernel dispatch, and no host sync happens until the caller
          materialises the result.  Passing host numpy straight to the jit
          serialises each transfer with its execution through the device
          transport (measured 25×+ slower on the tunneled chip); explicit
          puts let transfers queue ahead of compute.

        Rows past ``len(texts)`` are untouched ⇒ all-``U32_MAX``.
        """
        cfg, params = self.cfg, self.params
        block_fn = resolve_signature_fn(cfg.backend)  # validates the name
        use_oph = cfg.backend == "oph"
        if use_oph:
            from advanced_scrapper_tpu.ops.oph import densify, oph_raw_signatures

            block_fn = oph_raw_signatures  # densify AFTER the block combine

        import jax
        import jax.numpy as jnp

        from advanced_scrapper_tpu.cpu.hostbatch import (
            block_counts,
            encode_blocks_ranges,
        )
        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.ops.minhash import accumulate_block_signatures
        from advanced_scrapper_tpu.ops.shingle import U32_MAX

        tid = trace_id or trace.new_trace_id()
        raw = [to_bytes(t) for t in texts]
        n = len(raw)
        # Bucket the article count so combine compiles O(log N) variants, not
        # one per corpus size (same trick as the block-length axis).
        n_bucket = bucket_len(n, min_bucket=64)
        overlap = params.shingle_k - 1
        stride = cfg.block_len - overlap
        with stages.timed("encode"), trace.span(
            "dedup.encode", trace=tid, docs=n
        ):
            # Vectorised RANGE bucketing, one numpy pass, no per-article
            # Python loop.  Every document becomes one TAIL range (the
            # whole doc when it fits a single block) routed to the
            # power-of-two width bucket of the tail's length, plus — for
            # documents longer than block_len — one BODY range that encodes
            # as exactly its m−1 FULL blocks at block_len.  The block SET
            # (bytes, per-block lengths, owners) is identical to a
            # whole-document split, but the tail rides a fitted row instead
            # of a block_len-wide one: tail padding alone was ~30% of the
            # ragged regime's dispatched bytes (a 12 kB article's last
            # block averages ~50% zeros at 4096 width).  The corpus
            # flattens into ONE blob + offset table; width groups are cut
            # straight out of it by the range-native encoder.
            lens = np.fromiter(map(len, raw), np.int64, count=n)
            doc_off = np.zeros((n + 1,), dtype=np.int64)
            np.cumsum(lens, out=doc_off[1:])
            blob = b"".join(raw)
            m = block_counts(lens, cfg.block_len, overlap)
            tail_start = (m - 1) * stride
            tail_len = lens - tail_start
            body_sel = np.flatnonzero(m > 1)
            range_starts = np.concatenate(
                [doc_off[:n] + tail_start, doc_off[:n][body_sel]]
            )
            range_lens = np.concatenate(
                [tail_len, tail_start[body_sel] + overlap]
            )
            range_owner = np.concatenate(
                [np.arange(n, dtype=np.int64), body_sel]
            )
            range_width = np.concatenate([
                bucket_widths(tail_len, max_bucket=cfg.block_len),
                np.full((len(body_sel),), cfg.block_len, np.int64),
            ])
            order = np.argsort(range_width, kind="stable")
            sorted_w = range_width[order]
            n_ranges = len(order)
            group_lo = (
                np.flatnonzero(np.r_[True, sorted_w[1:] != sorted_w[:-1]])
                if n_ranges
                else np.zeros((0,), np.int64)
            )

        def host_batches():
            # a generator: encode stays lazy, overlapping device dispatch
            # in both consumption modes below
            for g, lo in enumerate(group_lo):
                hi = group_lo[g + 1] if g + 1 < len(group_lo) else n_ranges
                idx = order[lo:hi]
                w = int(sorted_w[lo])
                with stages.timed("encode"):
                    r_starts = range_starts[idx]
                    r_lens = range_lens[idx]
                    enc = encode_blocks_ranges(
                        blob, r_starts, r_lens,
                        block_counts(r_lens, w, overlap), w, overlap,
                    )
                    if enc is None:  # no compiler: per-group Python slices
                        r_doc = range_owner[idx]
                        rel = r_starts - doc_off[r_doc]
                        enc = encode_blocks(
                            [
                                raw[d][s : s + ln]
                                for d, s, ln in zip(
                                    r_doc.tolist(), rel.tolist(),
                                    r_lens.tolist(),
                                )
                            ],
                            w,
                            overlap=overlap,
                        )
                    tok, blk_lens, owners_local = enc
                    owners = range_owner[idx].astype(np.int32)[owners_local]
                n_blocks = tok.shape[0]
                # cfg.batch_size keeps its pre-bucketing meaning — the peak
                # device bytes per dispatch stay batch_size × block_len — so
                # the row count scales up as the width bucket narrows.
                bs = min(max(cfg.batch_size * cfg.block_len // w, 64), 16384)
                # Greedy power-of-two row chunks: full bs tiles, then the
                # tail decomposes into descending power-of-two dispatches
                # (≥64; the last one zero-pads).  A width group with 33
                # leftover blocks must not dispatch (and compute!) a
                # 16384-row tile — measured 2.5× of the ragged regime's
                # device bytes were tail padding at 2k articles.  Chunks,
                # not one bucketed tail tile: every corpus then draws from
                # the SAME O(log bs) shape set per width, so one warm corpus
                # compiles (almost) everything — a per-corpus bucketed tail
                # would trickle fresh shapes (and recompiles) into every
                # corpus that follows.
                start = 0
                while start < n_blocks:
                    remaining = n_blocks - start
                    rows = bs
                    if remaining < bs:
                        rows = 64
                        while rows * 2 <= remaining:
                            rows *= 2
                    t = tok[start : start + rows]
                    l = blk_lens[start : start + rows]
                    o = owners[start : start + rows]
                    if t.shape[0] < rows:
                        pad = rows - t.shape[0]
                        t = np.concatenate([t, np.zeros((pad, w), np.uint8)])
                        l = np.concatenate([l, np.zeros((pad,), np.int32)])
                        o = np.concatenate([o, np.zeros((pad,), np.int32)])
                    yield (t, l, o)
                    start += rows

        # put_workers > 1 (ASTPU_DEDUP_PUT_WORKERS; 0 = transport auto —
        # see resolve_put_workers) issues the H2D puts from a thread pool:
        # on transports where each put is a serialized round trip (see
        # DESIGN.md §5 stream-tuning note) concurrent puts overlap that
        # latency.  The min-combine is order-independent, so batch order
        # never matters; 1 keeps the original inline put→accumulate
        # interleaving untouched.
        put_workers = resolve_put_workers(cfg)
        running = jnp.full((n_bucket, params.num_perm), U32_MAX, jnp.uint32)
        dispatched = 0
        if put_workers > 1:
            # encode→h2d as a stage graph: pull workers draw width-group
            # batches off the (locked) encode generator and device_put
            # them concurrently; the capacity-1 ``staged`` edge bounds
            # resident tiles at put_workers (executing) + 1 (buffered)
            # + 1 (being accumulated) — the SAME window the hand-rolled
            # executor+deque enforced, now via the runtime's
            # backpressure.  The min-combine is order-independent, so
            # out-of-order staging never matters.
            from advanced_scrapper_tpu.runtime import DONE, StageGraph

            gen = host_batches()
            gen_lock = threading.Lock()

            def pull():
                with gen_lock:
                    return next(gen, DONE)

            def put(batch):
                t, l, o = batch
                with stages.timed("h2d"):
                    return jax.device_put(t), jax.device_put(l), jax.device_put(o)

            g = StageGraph("dedup.h2d")
            staged = g.edge("staged", capacity=1)
            g.stage(
                "h2d", source=pull, fn=put, out_edge=staged,
                workers=put_workers,
            )
            g.start()
            try:
                for t, l, o in staged:
                    dispatched += 1
                    with stages.timed("kernel"), self.step_timer.step(
                        int(t.shape[0])
                    ):
                        running = accumulate_block_signatures(
                            running, block_fn(t, l, params), o,
                            num_articles=n_bucket,
                        )
                if g.error is not None:
                    raise g.error  # the original worker exception, unwrapped
            finally:
                g.stop()
                g.join(timeout=30, raise_error=False)
        else:
            for t, l, o in host_batches():
                with stages.timed("h2d"):
                    t, l, o = (
                        jax.device_put(t), jax.device_put(l), jax.device_put(o)
                    )
                dispatched += 1
                with stages.timed("kernel"), self.step_timer.step(
                    int(t.shape[0])
                ):  # async dispatch; waits land here
                    running = accumulate_block_signatures(
                        running, block_fn(t, l, params), o, num_articles=n_bucket
                    )
        self._m_batches.inc(dispatched)
        if trace.RECORDER.active:
            trace.record(
                "span", "dedup.dispatch", trace=tid, batches=dispatched, docs=n
            )
        if use_oph:
            running = densify(running)
        return running

    def _prepare(self, texts: Sequence[str | bytes]):
        """Shared front half of both resolution paths: encode → device
        signatures → candidate keys → per-band candidates."""
        import jax

        from advanced_scrapper_tpu.obs import stages, trace

        tid = trace.new_trace_id()
        n = len(texts)
        raw = [to_bytes(t) for t in texts]  # encode once; identity on bytes
        sigs = self._signatures_device(raw, trace_id=tid)
        n_bucket = sigs.shape[0]
        lens = np.fromiter((len(r) for r in raw), np.int64, count=n)
        valid = np.zeros((n_bucket,), bool)
        valid[:n] = lens >= self.params.shingle_k
        valid = jax.device_put(valid)
        with stages.timed("resolve"), trace.span(
            "dedup.candidates", trace=tid, docs=n
        ):
            keys = candidate_keys(
                sigs, self.params.band_salt, self.cfg.cand_subbands
            )
            rep_bands = duplicate_rep_bands(keys, valid)
        if self.rerank_hook is not None:
            # the declared RERANK_HOOK_EDGE: candidates flow through the
            # rerank tier before EITHER resolution path sees them
            with trace.span("dedup.rerank", trace=tid, docs=n):
                rep_bands = self.rerank_hook(raw, sigs, rep_bands, valid)
        return raw, sigs, keys, valid, rep_bands, n_bucket, tid

    def dedup_reps_async(self, texts: Sequence[str | bytes], *, _regime: str = "async"):
        """Dispatch the full dedup and return the DEVICE ``int32[bucket]``
        rep array without syncing — everything from encode to resolve is
        async, so a caller streaming multiple corpora overlaps corpus i+1's
        H2D/compute with corpus i's readback (the production firehose
        regime; one-shot callers use :meth:`dedup_reps`).  Rows past
        ``len(texts)`` are padding (invalid ⇒ self-assigned).

        This path never syncs, so borderline edges are handled by the
        estimator-only ``fine_margin`` bar — the exact-Jaccard
        confirmation stage needs a host round trip and lives in the
        one-shot :meth:`dedup_reps` (measured trade in DESIGN.md §2e).
        """
        # Device-resident end to end: combined signatures never round-trip to
        # the host (the sig D2H + re-H2D bounce cost ~0.3 s per 8k articles
        # on the tunneled link); the only D2H is the final int32[N] reps.
        from advanced_scrapper_tpu.obs import stages, trace

        _raw, sigs, keys, valid, rep_bands, n_bucket, tid = self._prepare(texts)
        # _regime: the one-shot API's estimator-only branch delegates here —
        # its documents must count as "oneshot", not inflate the async series
        self._m_docs[_regime].inc(len(texts))
        with stages.timed("resolve"), trace.span(
            "dedup.resolve", trace=tid, regime=_regime, docs=len(texts)
        ):
            if self.cfg.cand_subbands and self.cfg.fine_margin:
                thr = fine_edge_thresholds(
                    rep_bands,
                    keys,
                    self.cfg.sim_threshold,
                    self.cfg.fine_margin,
                    num_coarse=self.params.num_bands,
                )
            else:
                thr = self.cfg.sim_threshold
            return resolve_rep_bands(
                rep_bands, sigs, valid, thr, jump_rounds=_jump_rounds(n_bucket)
            )

    def dedup_reps_sharded(self, texts: Sequence[str | bytes], mesh) -> np.ndarray:
        """int32[N] representatives via the mesh-sharded FUSED step: blockwise
        encode → ``parallel.sharded.make_sharded_block_dedup`` (per-article
        segment-min combined with ``lax.pmin`` inside the device step, then
        LSH resolution) — the multi-device path with NO host-side combine
        pass between the encoder and resolution.  Same estimator-only
        resolution semantics as :meth:`dedup_reps_async` (parity-tested);
        use the one-shot :meth:`dedup_reps` when the exact-verify precision
        path is required.
        """
        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.parallel.sharded import (
            make_sharded_block_dedup,
        )

        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        tid = trace.new_trace_id()
        self._m_docs["sharded"].inc(n)
        cfg = self.cfg
        raw = [to_bytes(t) for t in texts]
        with stages.timed("encode"):
            tok, lens, owners = encode_blocks(
                raw, cfg.block_len, overlap=self.params.shingle_k - 1
            )
            owners = owners.astype(np.int32)
            n_bucket = bucket_len(n, min_bucket=64)
            # shard divisibility + bucketed block axis: pad rows to the
            # scratch article slot (owner n_bucket → sliced off on device)
            ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
            rows = bucket_len(max(tok.shape[0], ndev), min_bucket=64)
            rows = -(-rows // ndev) * ndev  # exact multiple for odd meshes
            if tok.shape[0] < rows:
                pad = rows - tok.shape[0]
                tok = np.concatenate(
                    [tok, np.zeros((pad, cfg.block_len), np.uint8)]
                )
                lens = np.concatenate([lens, np.zeros((pad,), np.int32)])
                owners = np.concatenate(
                    [owners, np.full((pad,), n_bucket, np.int32)]
                )
        key = (mesh, n_bucket, cfg.block_len)
        step = self._sharded_steps.get(key)
        if step is None:
            step = make_sharded_block_dedup(
                mesh,
                self.params,
                n_bucket,
                threshold=cfg.sim_threshold,
                jump_rounds=_jump_rounds(n_bucket),
                backend=cfg.backend,
                cand_subbands=cfg.cand_subbands,
                fine_margin=cfg.fine_margin,
            )
            self._sharded_steps[key] = step
        with self.step_timer.step(int(tok.shape[0])):
            rep, _hist = step(tok, lens, owners)
        self._m_batches.inc()
        with stages.timed("resolve"), trace.span(
            "dedup.resolve", trace=tid, regime="sharded", docs=n
        ):
            out = np.asarray(rep)[:n]
        self._count_result("sharded", n, out)
        return out

    def _exact_verified_ok(self, raw, sigs, keys, valid, rep_bands):
        """Verified-edge matrix with statistically fragile edges confirmed
        (or killed) by EXACT shingle-set Jaccard.

        The estimator cannot meet the precision budget alone: at 128 perms
        its σ≈0.04, and the borderline band [0.70, 0.72) holds both the
        false merges (true J < 0.7, the r4 ~3.2-point precision giveback)
        and the genuine bridges that recover cross-estimator disagreement
        recall (measured frontier: tools/sweep_fine_margin.py).  Exact
        Jaccard — the oracle's own ``shingle_set``/``jaccard`` definition,
        imported so the two can never diverge — separates them perfectly,
        and the flagged set is tiny (~130 pairs per 2048 docs), so the
        host cost is noise in the one-shot path.  Returns the device
        ``ok`` matrix (agreement pass runs ONCE) with refuted edges
        cleared, ready for ``resolve_rep_bands_from_ok``.
        """
        from advanced_scrapper_tpu.cpu.oracle import jaccard, shingle_set

        need_dev, ok_dev = borderline_edge_mask(
            rep_bands,
            sigs,
            keys,
            valid,
            self.cfg.sim_threshold,
            self.cfg.exact_verify_band,
            num_coarse=self.params.num_bands,
        )
        from advanced_scrapper_tpu.obs.telemetry import NOOP

        need = np.asarray(need_dev)
        if self._m_cand is not NOOP:
            # metric-only host work (skipped when telemetry is disabled),
            # counted BEFORE the borderline early-return: candidate volume
            # must not read 0 just because every edge cleared the bar
            rb_m = np.asarray(rep_bands)
            self._m_cand.inc(
                int((rb_m != np.arange(rb_m.shape[0])[:, None]).sum())
            )
            self._m_borderline.inc(int(need.sum()))
        if not need.any():
            return ok_dev
        rb = np.asarray(rep_bands)
        ok = np.asarray(ok_dev).copy()
        pairs = {}  # (lo, hi) -> verdict; an edge is undirected
        shingles: dict[int, set] = {}

        def sset(i: int) -> set:
            if i not in shingles:
                shingles[i] = shingle_set(raw[i], self.params.shingle_k)
            return shingles[i]

        checked = 0
        sigs_np = None
        for r, c in zip(*np.nonzero(need)):
            j = int(rb[r, c])
            key = (min(int(r), j), max(int(r), j))
            if key not in pairs:
                if checked >= self.cfg.exact_verify_cap:
                    # past the cap (pathological all-borderline corpora)
                    # the edge keeps an ESTIMATOR verdict — but at the
                    # strict fine-only bar (base + fine_margin) the
                    # estimator-only paths apply, not plain base: the
                    # certified path must never verify a flagged edge
                    # more laxly than the uncertified ones do
                    if sigs_np is None:
                        sigs_np = np.asarray(sigs)
                    agree = float(
                        (sigs_np[key[0]] == sigs_np[key[1]]).mean()
                    )
                    pairs[key] = agree >= (
                        self.cfg.sim_threshold + self.cfg.fine_margin
                    )
                else:
                    checked += 1
                    pairs[key] = (
                        jaccard(sset(key[0]), sset(key[1]))
                        >= self.cfg.sim_threshold
                    )
            if not pairs[key]:
                ok[r, c] = False  # exact Jaccard (or strict bar) refuted it
        self._m_exact_checks.inc(checked)
        return ok

    def dedup_reps(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """int32[N] first-seen-wins representative per text (union-find
        roots), with exact-Jaccard confirmation of statistically fragile
        edges (``exact_verify_band``) — the certified precision path."""
        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        from advanced_scrapper_tpu.obs import trace

        # exact verification is independent of fine-band candidacy:
        # coarse-borderline edges need confirmation even at cand_subbands=0
        # (borderline_edge_mask handles the no-fine-columns case)
        if not self.cfg.exact_verify_band:
            out = np.asarray(self.dedup_reps_async(texts, _regime="oneshot"))[:n]
            self._count_result("oneshot", n, out)
            return out
        raw, sigs, keys, valid, rep_bands, n_bucket, tid = self._prepare(texts)
        self._m_docs["oneshot"].inc(n)
        with trace.span("dedup.resolve", trace=tid, regime="oneshot", docs=n):
            ok = self._exact_verified_ok(raw, sigs, keys, valid, rep_bands)
            rep = resolve_rep_bands_from_ok(
                rep_bands, ok, valid, jump_rounds=_jump_rounds(n_bucket)
            )
            out = np.asarray(rep)[:n]
        self._count_result("oneshot", n, out)
        return out

    def keep(self, texts: Sequence[str | bytes]) -> np.ndarray:
        reps = self.dedup_reps(texts)
        return reps == np.arange(len(reps))

    def open_stream_index(self, index_dir: str):
        """Open the durable stream index this engine's config names: a
        local :class:`~advanced_scrapper_tpu.index.store.PersistentIndex`
        under ``index_dir``, or — when ``cfg.index_fleet`` is set — a
        :class:`~advanced_scrapper_tpu.index.fleet.ShardedIndexClient`
        over the remote shard fleet (``index_dir`` then holds only the
        degraded-mode spill journals).  Either return value is a valid
        ``index`` argument to :meth:`dedup_against_index` — the fleet is
        a config string, not a call-site change."""
        if self.cfg.index_fleet:
            from advanced_scrapper_tpu.index.fleet import open_fleet_index

            return open_fleet_index(self.cfg, index_dir, space="bands")
        from advanced_scrapper_tpu.index import PersistentIndex

        return PersistentIndex(
            index_dir,
            cut_postings=self.cfg.index_cut_postings,
            compact_segments=self.cfg.index_compact_segments,
        )

    def dedup_against_index(
        self, texts: Sequence[str | bytes], index, doc_ids=None
    ) -> np.ndarray:
        """``int64[N]`` attribution of a corpus against a persistent index
        (``index.store.PersistentIndex`` — or its fleet drop-in,
        ``index.fleet.ShardedIndexClient``; ``open_stream_index`` picks by
        config): device signatures → wide uint64 band keys →
        ``check_and_add_batch``.  A row whose result is ≥ 0 is
        a near-dup of that (possibly restarts-old) doc id; fresh rows post
        their keys under ``doc_ids`` (allocated from the index when not
        given) and return -1.  Sub-shingle rows are never probed or posted
        (always -1) — same eligibility rule as every stream index.

        This is the engine-level streaming entry the persistent index was
        built for: the batch backend (`extractors/tpu_batch.py`) wraps it
        with record bookkeeping, but a raw corpus stream can consume it
        directly.
        """
        from advanced_scrapper_tpu.ops.lsh import band_keys_wide
        from advanced_scrapper_tpu.utils.bloom import pack_keys64

        n = len(texts)
        out = np.full((n,), -1, np.int64)
        if n == 0:
            return out
        raw = [to_bytes(t) for t in texts]
        sigs = self.signatures(raw)
        keys64 = pack_keys64(
            np.asarray(band_keys_wide(sigs, self.params.band_salt))
        )
        eligible = np.fromiter(
            (len(r) >= self.params.shingle_k for r in raw), bool, n
        )
        if not eligible.any():
            return out
        if doc_ids is None:
            doc_ids = index.allocate_doc_ids(n)
        doc_ids = np.asarray(doc_ids, dtype=np.uint64)
        out[eligible] = index.check_and_add_batch(
            keys64[eligible], doc_ids[eligible]
        )
        return out


class ExactDedup:
    """First-seen exact dedup with a byte-identical guarantee.

    Default path: ONE native pass (``cpu.hostbatch.exact_keep_first_native``)
    — the corpus flattens into a single byte blob + offset table and a
    C-side open-addressing hash table decides first-seen membership,
    settling every hash-equal probe with a full ``memcmp`` (a collision can
    lengthen a probe chain but never drop a distinct row).  This is the
    pandas ``drop_duplicates(keep='first')`` replacement that actually
    out-runs pandas: no per-row Python objects, no device round trip, one
    preallocated uint64 offset array and one uint8 keep mask.

    Fallback (no compiler, mixed str/bytes input, or a caller-supplied
    hasher): the device proposes equality groups via 128-bit hashes and the
    host walks each group in original order comparing *actual* full strings
    — including past any hash-side truncation — so the kept index set
    equals the pandas path exactly on every route.
    """

    def __init__(self, hasher: ExactHasher | None = None, max_len: int = 4096):
        # A caller-supplied hasher pins the grouping path (tests inject
        # degenerate hashers; the native pass would ignore them).
        self._custom_hasher = hasher is not None
        self.hasher = hasher or ExactHasher()
        # Historical name: rows are hashed blockwise at this width, so it no
        # longer caps item length — any size hashes exactly (the linear hash
        # splits across blocks; see ``ExactHasher.hash_docs``).
        self.max_len = max_len

    def keep_indices(self, items: Sequence[str]) -> list[int]:
        if not items:
            return []
        if not self._custom_hasher:
            from advanced_scrapper_tpu.cpu.exactdedup import keep_first_list
            from advanced_scrapper_tpu.cpu.hostbatch import (
                exact_keep_first_native,
            )

            # zero-copy tier first (reads str/bytes buffers in place), then
            # the blob tier (one join + offsets); both confirm every
            # hash-equal probe with a full memcmp, so each is byte-identical
            # to the pandas path on the inputs it accepts
            keep = keep_first_list(items)
            if keep is None:
                keep = exact_keep_first_native(items)
            if keep is not None:
                return np.flatnonzero(keep).tolist()
        n = len(items)
        raw = [to_bytes(s) for s in items]
        block = bucket_len(max(1, min(max(len(r) for r in raw), self.max_len)))
        h = self.hasher.hash_docs(raw, block_len=block)  # uint32[N, 4]
        # Group rows by their 128-bit hash with one C-speed lexsort instead
        # of a per-row Python dict walk: rows whose hash is unique are kept
        # outright, and only multi-member groups (true duplicates or 2⁻¹²⁸
        # collisions) ever reach the Python string-confirm below.
        hi = (h[:, 0].astype(np.uint64) << 32) | h[:, 1]
        lo = (h[:, 2].astype(np.uint64) << 32) | h[:, 3]
        order = np.lexsort((lo, hi))  # stable ⇒ ties stay in original order
        shi, slo = hi[order], lo[order]
        new_group = np.empty(n, bool)
        new_group[0] = True
        new_group[1:] = (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])
        gid = np.empty(n, np.int64)
        gid[order] = np.cumsum(new_group) - 1
        # per-group leader = smallest original index (stability of lexsort)
        leader_of = order[np.flatnonzero(new_group)]
        counts = np.bincount(gid)
        keep = counts[gid] == 1  # singleton hash ⇒ provably first-seen unique
        multi_rows = np.flatnonzero(~keep)  # ascending ⇒ original order
        if len(multi_rows):
            # The overwhelming case is a true-duplicate group: every member
            # equals its leader.  One C-level object compare settles all of
            # them; only groups holding a member that DIFFERS from the
            # leader (a 2⁻¹²⁸ hash collision) take the per-group walk.
            obj = np.array(items, dtype=object)
            leaders = leader_of[gid[multi_rows]]
            eq_leader = obj[multi_rows] == obj[leaders]
            keep[leader_of] = True  # singleton leaders were already True
            rare = np.unique(gid[multi_rows[~eq_leader]])
            for g in rare.tolist():
                members = multi_rows[gid[multi_rows] == g]
                kept_distinct: list[int] = []
                for i in members.tolist():
                    if not any(items[j] == items[i] for j in kept_distinct):
                        kept_distinct.append(i)
                        keep[i] = True
                    else:
                        keep[i] = False
        return np.flatnonzero(keep).tolist()

    def keep_mask(self, items: Sequence[str]) -> np.ndarray:
        mask = np.zeros(len(items), dtype=bool)
        mask[self.keep_indices(items)] = True
        return mask
