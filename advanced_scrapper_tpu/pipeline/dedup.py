"""Dedup engines — the TPU rerouting of the reference's dedup steps.

- :class:`NearDupEngine` — MinHash(k=5, 128-perm) + 16-band LSH near-dup
  clustering (the north-star workload; no analogue in the reference, which
  only ever does exact dedup).
- :class:`ExactDedup` — byte-identical replacement for pandas
  ``drop_duplicates(subset=['url'], keep='first')``
  (``yahoo_links_selenium.py:79,174``): 128-bit device hashing proposes
  groups, the host confirms true string equality inside each group, so the
  surviving row set is *provably* identical to the pandas path.
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import MinHashParams, make_params
from advanced_scrapper_tpu.core.tokenizer import (
    bucket_len,
    bucket_widths,
    encode_blocks,
    to_bytes,
)
from advanced_scrapper_tpu.ops.exact import ExactHasher
from advanced_scrapper_tpu.ops.lsh import (
    borderline_edge_mask,
    fine_edge_thresholds,
    resolve_rep_bands,
    resolve_rep_bands_from_ok,
)
from advanced_scrapper_tpu.ops.minhash import resolve_signature_fn


#: the DECLARED hook edge for ROADMAP item 2's candidate-verification
#: (rerank) tier: in the dedup stage graph (encode → h2d → kernel →
#: candidates → resolve) this names the edge between candidate generation
#: and union-find resolution.  A :attr:`NearDupEngine.rerank_hook`
#: callable ``(raw, sigs, rep_bands, valid) -> rep_bands`` slots in here —
#: BOTH resolution paths (async/estimator-only and the certified one-shot)
#: route their candidate matrix through it before resolving, so a
#: device-batched exact-Jaccard rerank tier becomes a graph edge, not a
#: bespoke rewrite of either path.
RERANK_HOOK_EDGE = "dedup.candidates->dedup.resolve"


_LSH_EPILOGUES: dict = {}


def _lsh_epilogue(name: str):
    """``ops.lsh``'s fused end-of-corpus epilogues, wrapped ONCE in the
    recompile sentinel (``obs/devprof.py``) under ``kernel="dedup_<name>"``
    — so a steady-state epilogue recompile (e.g. a silently-varying
    ``num_articles`` bucket) is as countable as a tile-step one.  Lazy
    (the epilogues are jitted at ``ops.lsh`` import, which pulls jax) and
    memoised (the wrapper is per-process, like the underlying jit
    cache)."""
    fn = _LSH_EPILOGUES.get(name)
    if fn is None:
        from advanced_scrapper_tpu.obs import devprof
        from advanced_scrapper_tpu.ops import lsh

        fn = devprof.instrument_jit(getattr(lsh, name), f"dedup_{name}")
        _LSH_EPILOGUES[name] = fn
    return fn


def _jump_rounds(n: int) -> int:
    r = 1
    while (1 << r) < n:
        r += 1
    return r


def _tile_bs(cfg: DedupConfig, width: int) -> int:
    """Full-tile row count for a width bucket.  ``cfg.batch_size`` keeps
    its pre-bucketing meaning — peak device bytes per dispatch stay
    ``batch_size × block_len`` — so rows scale up as the bucket narrows.
    THE single source of the formula: the encode chunker and
    :meth:`NearDupEngine.prewarm` must draw from the same shape set, or
    prewarming silently compiles a disjoint set and defeats itself."""
    return min(max(cfg.batch_size * cfg.block_len // width, 64), 16384)


def _tile_rows_options(bs: int) -> list[int]:
    """Every row count the greedy chunker can emit for a width bucket:
    the full tile plus the descending power-of-two tail chunks (≥64) —
    the O(log bs) shape set prewarm compiles
    (``core.tokenizer.tile_rows_options``, shared with the matcher's
    screen tile plane)."""
    from advanced_scrapper_tpu.core.tokenizer import tile_rows_options

    return tile_rows_options(bs, 64)


def _prewarm_widths(cfg: DedupConfig) -> list[int]:
    """The chunker's width-bucket set: powers of two below ``block_len``
    (mirroring ``bucket_widths(..., max_bucket=block_len)``) plus
    ``block_len`` itself — the body/long-tail bucket, which need not be a
    power of two and must not be skipped or prewarm misses the dominant
    width.  THE single source for every prewarm (single-device and
    mesh-sharded): a width added to the chunker without landing here
    would silently disjoint the prewarmed set (the PR 9 lesson)."""
    widths = []
    w = 64
    while w < cfg.block_len:
        widths.append(w)
        w *= 2
    widths.append(cfg.block_len)
    return widths


#: dispatch knobs the perf-ledger profile may resolve, with the explicit
#: env key that always wins over a ledger row
_KNOB_PROFILE_FIELDS: tuple[tuple[str, str], ...] = (
    ("put_workers", "ASTPU_DEDUP_PUT_WORKERS"),
    ("dispatch_window", "ASTPU_DEDUP_DISPATCH_WINDOW"),
    ("rerank_tile_rows", "ASTPU_DEDUP_RERANK_TILE_ROWS"),
)


def _resolve_knob_profile(cfg: DedupConfig) -> DedupConfig:
    """Per-platform knob-profile store: fill still-default dispatch knobs
    from the perf ledger's best same-platform sweep row.

    Resolution order per knob (unit-tested in ``tests/test_perf_obs.py``):

    1. explicit env (``ASTPU_DEDUP_PUT_WORKERS`` etc.) — always wins,
       applied here so a directly-constructed ``DedupConfig()`` honours
       it exactly like a ``config.from_env`` one;
    2. a caller-pinned config value (field differs from the dataclass
       default) — the constructor argument is an explicit choice;
    3. the best same-platform row of ``$ASTPU_PERF_LEDGER``
       (``obs.perfdb.best_knob_profile`` — max articles/sec sweep row
       whose platform partition matches this process's jax backend);
    4. the dataclass default (no ledger / no matching row / no knob in
       the winning row's tag) — current constants, unchanged.
    """
    import dataclasses

    defaults = DedupConfig()
    env_updates: dict[str, int] = {}
    open_knobs: list[str] = []
    for f, env_key in _KNOB_PROFILE_FIELDS:
        raw = os.environ.get(env_key)
        if raw is not None:
            try:
                env_updates[f] = int(raw)
            except ValueError:
                pass  # malformed env: leave the field as constructed
            continue
        if getattr(cfg, f) == getattr(defaults, f):
            open_knobs.append(f)
    if env_updates:
        cfg = dataclasses.replace(cfg, **env_updates)
    path = os.environ.get("ASTPU_PERF_LEDGER", "")
    if not path or not os.path.exists(path) or not open_knobs:
        return cfg
    try:
        import jax

        from advanced_scrapper_tpu.obs.perfdb import best_knob_profile

        profile = best_knob_profile(path, jax.devices()[0].platform)
    except Exception:  # a torn/foreign ledger must never fail engine init
        return cfg
    updates = {
        k: v for k, v in profile.items() if k in open_knobs and v
    }
    return dataclasses.replace(cfg, **updates) if updates else cfg


def resolve_put_workers(cfg: DedupConfig) -> int:
    """Effective H2D put-thread count: ``cfg.put_workers``, with 0 meaning
    the transport default (``core.mesh.auto_h2d_workers`` — 4 on the
    serializing axon tunnel, 1 on local backends).  Lives in the engine so
    production configs and bench defaults resolve identically."""
    if cfg.put_workers:
        return cfg.put_workers
    from advanced_scrapper_tpu.core.mesh import auto_h2d_workers

    return auto_h2d_workers()


class NearDupEngine:
    """Batch near-duplicate detector.

    Long texts are split into overlapping blocks (`core.tokenizer.encode_blocks`)
    so device shapes stay fixed; block signatures are exactly min-combined per
    article. Block batches are padded to a fixed size to avoid recompilation.
    """

    def __init__(self, cfg: DedupConfig | None = None, params: MinHashParams | None = None):
        self.cfg = cfg or DedupConfig()
        self.params = params or make_params(
            num_perm=self.cfg.num_perm,
            num_bands=self.cfg.num_bands,
            shingle_k=self.cfg.shingle_k,
            seed=self.cfg.seed,
        )
        # compiled fused-step cache for dedup_reps_sharded, keyed on
        # (mesh, article bucket, block_len) — meshes are hashable
        self._sharded_steps: dict = {}
        #: the single-dispatch packed tile step (ops.minhash.
        #: make_fused_tile_step), built lazily — params constant-fold in
        self._fused_step = None
        # per-platform knob-profile resolution (perf-ledger defaults):
        # still-default dispatch knobs pick up the best same-platform
        # sweep row's values; explicit env / caller-pinned fields win
        self.cfg = _resolve_knob_profile(self.cfg)
        #: the rerank tier's slot on :data:`RERANK_HOOK_EDGE` — when set,
        #: every resolution path passes its candidate matrix through it
        #: before union-find (None = pass-through)
        self.rerank_hook = None
        #: whether the LAST corpus's candidates actually passed through
        #: an AUTHORITATIVE tier (settled true-Jaccard verdicts): the
        #: certified path then resolves the rewritten matrix verbatim
        #: instead of re-litigating edges with the estimator-era
        #: exact-verify stage.  False whenever the hook is absent,
        #: bypassed by the skip_rerank brownout, or non-authoritative.
        self._rerank_applied = False
        #: the default precision tier (pipeline/rerank.py) when
        #: ``cfg.rerank`` — kept as an attribute so callers can attach a
        #: persistent index for the borderline ANN re-probe or read the
        #: per-corpus settlement stats; ``rerank_hook = None`` (or
        #: ASTPU_DEDUP_RERANK=0) remains the opt-out
        self.rerank_tier = None
        if self.cfg.rerank:
            from advanced_scrapper_tpu.pipeline.rerank import RerankTier

            self.rerank_tier = RerankTier(self.cfg, self.params)
            self.rerank_hook = self.rerank_tier
        #: optional :class:`~advanced_scrapper_tpu.runtime.admission.
        #: DegradationLadder` — when installed, the engine honours the
        #: declared brownout steps at its decision points: a halved
        #: dispatch window ("shrink_window"), a bypassed rerank tier
        #: ("skip_rerank"), and half the LSH bands on the stream-index
        #: path ("fewer_bands"); each application is counted via
        #: ``ladder.count_effect`` and reverses the moment the step exits
        self.ladder = None
        #: optional per-tile observer ``(dict) -> None`` on the dispatch
        #: executor loop (tile index, rows, width, h2d_bytes, put/dispatch
        #: ms) — ``tools/profile_hostpath.py --device`` renders it as a
        #: timeline; None = no per-tile host work
        self.dispatch_probe = None
        #: tiles dispatched by the most recent corpus (set by
        #: ``_accumulate_device``; the per-tile traffic gate divides the
        #: device counters by this)
        self.last_tiles = 0
        self._instrument()
        # ASTPU_DEDUP_PREWARM=N; initialises jax.  1 = default (one
        # batch_size corpus); >1 pins the expected per-corpus article
        # count, whose bucket the step set keys on.  Pointless under the
        # legacy transport (it never dispatches the fused step), so the
        # escape-hatch combination skips it instead of burning compiles.
        if self.cfg.prewarm and self.cfg.packed_h2d:
            self.prewarm(None if self.cfg.prewarm == 1 else self.cfg.prewarm)

    def _instrument(self) -> None:
        """Telemetry handles (no-ops when disabled) + the production home
        of the once-orphaned ``StepTimer``: every device block-batch
        dispatch lands in it, so ``step_summary()`` answers "what is the
        per-dispatch latency right now" on a live engine."""
        from advanced_scrapper_tpu.obs import telemetry
        from advanced_scrapper_tpu.obs.profiler import StepTimer

        self.step_timer = StepTimer(
            histogram=telemetry.histogram(
                "astpu_dedup_step_seconds", "device block-batch dispatch latency"
            )
        )
        self._m_batches = telemetry.counter(
            "astpu_dedup_batches_total", "device block batches dispatched"
        )
        self._m_docs = {
            regime: telemetry.counter(
                "astpu_dedup_docs_total",
                "documents entering dedup",
                regime=regime,
            )
            for regime in ("oneshot", "async", "sharded")
        }
        self._m_dups = {
            regime: telemetry.counter(
                "astpu_dedup_dups_total",
                "documents resolved as near-duplicates",
                regime=regime,
            )
            for regime in ("oneshot", "sharded")
        }
        self._m_ratio = {
            regime: telemetry.gauge(
                "astpu_dedup_ratio",
                "last corpus' duplicate fraction",
                regime=regime,
            )
            for regime in ("oneshot", "sharded")
        }
        self._m_cand = telemetry.counter(
            "astpu_dedup_candidate_pairs_total",
            "LSH candidate (row, band) hits examined by the certified "
            "one-shot resolution (async/sharded never sync candidates)",
        )
        self._m_borderline = telemetry.counter(
            "astpu_dedup_borderline_edges_total",
            "estimator-fragile edges flagged for exact confirmation",
        )
        self._m_exact_checks = telemetry.counter(
            "astpu_dedup_exact_checks_total",
            "exact shingle-set Jaccard confirmations run",
        )

    def step_summary(self) -> dict:
        """Rolling per-dispatch latency/throughput (``StepTimer.summary``)."""
        return self.step_timer.summary()

    def _count_result(self, regime: str, n: int, reps: np.ndarray) -> None:
        """Host-side dedup-ratio accounting — only for paths that already
        synced ``reps`` (the async path never syncs; it counts docs only).
        The numpy reduction is metric-only work, so it is skipped entirely
        when telemetry handed out no-op handles (the disabled cost model)."""
        from advanced_scrapper_tpu.obs.telemetry import NOOP

        if n == 0 or self._m_dups[regime] is NOOP:
            return
        dups = int((reps[:n] != np.arange(n)).sum())
        self._m_dups[regime].inc(dups)
        self._m_ratio[regime].set(dups / n)

    def signatures(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """uint32[N, num_perm] MinHash signatures (blockwise, batched).

        With ``cfg.backend == "oph"`` block signatures are the *raw* OPH
        form (empty bins ``U32_MAX``) so the per-article segment-min combine
        stays exact; densification runs once after the combine (see
        ``ops/oph.py`` for why that order is load-bearing).
        """
        if len(texts) == 0:
            return np.zeros((0, self.params.num_perm), np.uint32)
        from advanced_scrapper_tpu.obs import stages, trace

        tid = trace.new_trace_id()
        sigs = self._signatures_device(texts, trace_id=tid)
        with stages.timed("kernel"), trace.span(
            "dedup.readback", trace=tid, docs=len(texts)
        ):  # readback sync: the device drains here
            return np.asarray(sigs)[: len(texts)]

    def _signatures_device(self, texts: Sequence[str | bytes], trace_id=None):
        """Device ``uint32[bucket_len(N), num_perm]`` combined signatures
        (densified for the OPH backend): :meth:`_accumulate_device` plus
        the one densify dispatch the raw OPH accumulator defers.  The
        resolution paths skip this and fold the densify into their fused
        epilogue instead (:meth:`_prepare`)."""
        running, _n_bucket, use_oph = self._accumulate_device(
            texts, trace_id=trace_id
        )
        if use_oph:
            from advanced_scrapper_tpu.obs import stages
            from advanced_scrapper_tpu.ops.oph import densify

            running = densify(running)
            stages.count_dispatch("dedup")
        return running

    def _get_fused_step(self):
        """The engine's single-dispatch tile step (params constant-folded;
        built once — jit caches per static (rows, width, num_articles)).
        Wrapped in the recompile sentinel (``obs/devprof.py``): every
        jit-cache miss counts on ``astpu_jit_compiles_total{kernel=
        "dedup_fused_tile"}`` — prewarm/warmup compiles are expected
        counts, a steady-state increment is the stall prewarm exists to
        prevent, tier-1-asserted at zero."""
        step = self._fused_step
        if step is None:
            from advanced_scrapper_tpu.obs import devprof
            from advanced_scrapper_tpu.ops.minhash import make_fused_tile_step

            step = devprof.instrument_jit(
                make_fused_tile_step(self.params, self.cfg.backend),
                "dedup_fused_tile",
            )
            self._fused_step = step
        return step

    def prewarm(self, n_articles: int | None = None) -> int:
        """Compile the packed tile-step shape set ahead of the first
        corpus: every width bucket's full tile plus its descending
        power-of-two tail chunks — the same O(log bs)-per-width shape set
        ``_accumulate_device`` draws from.  Returns the number of shape
        variants compiled.  Initialises the jax backend.

        ``n_articles`` pins the article-axis bucket (default: one
        ``batch_size`` corpus) and the pin is LOAD-BEARING: the fused
        step is compiled per static ``num_articles = bucket_len(N)``, so
        only corpora whose article count buckets the same skip their
        per-shape compiles — prewarm with the corpus size you will
        actually stream (``ASTPU_DEDUP_PREWARM=<count>``).  With
        ``ASTPU_COMPILE_CACHE`` set the compiles persist across
        processes and later prewarms (any bucket) are cache loads.
        """
        import jax.numpy as jnp

        from advanced_scrapper_tpu.core.mesh import maybe_enable_compile_cache
        from advanced_scrapper_tpu.ops.pack import packed_nbytes
        from advanced_scrapper_tpu.ops.shingle import U32_MAX

        maybe_enable_compile_cache()
        cfg = self.cfg
        n_bucket = bucket_len(
            n_articles if n_articles else cfg.batch_size, min_bucket=64
        )
        step = self._get_fused_step()
        compiled = 0
        for w in _prewarm_widths(cfg):
            # same derivation as the encode chunker (_tile_bs /
            # _tile_rows_options) — shared helpers, never re-derived here
            for rows in _tile_rows_options(_tile_bs(cfg, w)):
                running = jnp.full(
                    (n_bucket, self.params.num_perm), U32_MAX, jnp.uint32
                )
                packed = jnp.zeros((packed_nbytes(rows, w),), jnp.uint8)
                step(
                    running, packed, rows=rows, width=w, num_articles=n_bucket
                ).block_until_ready()
                compiled += 1
        if self.rerank_tier is not None:
            # the precision tier's settle tiles ride the same shared
            # tile_rows_options derivation — prewarm them (plus the
            # finalize) here so a first real corpus leaves the recompile
            # sentinel flat
            compiled += self.rerank_tier.prewarm()
        return compiled

    def _host_tiles(self, raw: list, trace_id=None):
        """Width-bucketed power-of-two tile generator ``(tok, lens,
        owners)`` — THE shared encode chunker of every packed dedup
        plane: the single-device executor (:meth:`_accumulate_device`)
        and the mesh-sharded one (:meth:`_accumulate_device_sharded`)
        both draw tiles from here, so their shape sets — and the
        prewarmed sets (``_tile_bs``/``_tile_rows_options``, shared) —
        can never silently diverge.  The eager prologue (vectorised
        range bucketing) runs on the caller's thread; per-group encode
        and greedy chunking run lazily where the consumer pulls."""
        cfg, params = self.cfg, self.params

        from advanced_scrapper_tpu.cpu.hostbatch import (
            block_counts,
            encode_blocks_ranges,
        )
        from advanced_scrapper_tpu.obs import stages, trace

        n = len(raw)
        overlap = params.shingle_k - 1
        stride = cfg.block_len - overlap
        with stages.timed("encode"), trace.span(
            "dedup.encode", trace=trace_id, docs=n
        ):
            # Vectorised RANGE bucketing, one numpy pass, no per-article
            # Python loop.  Every document becomes one TAIL range (the
            # whole doc when it fits a single block) routed to the
            # power-of-two width bucket of the tail's length, plus — for
            # documents longer than block_len — one BODY range that encodes
            # as exactly its m−1 FULL blocks at block_len.  The block SET
            # (bytes, per-block lengths, owners) is identical to a
            # whole-document split, but the tail rides a fitted row instead
            # of a block_len-wide one: tail padding alone was ~30% of the
            # ragged regime's dispatched bytes (a 12 kB article's last
            # block averages ~50% zeros at 4096 width).  The corpus
            # flattens into ONE blob + offset table; width groups are cut
            # straight out of it by the range-native encoder.
            lens = np.fromiter(map(len, raw), np.int64, count=n)
            doc_off = np.zeros((n + 1,), dtype=np.int64)
            np.cumsum(lens, out=doc_off[1:])
            blob = b"".join(raw)
            m = block_counts(lens, cfg.block_len, overlap)
            tail_start = (m - 1) * stride
            tail_len = lens - tail_start
            body_sel = np.flatnonzero(m > 1)
            range_starts = np.concatenate(
                [doc_off[:n] + tail_start, doc_off[:n][body_sel]]
            )
            range_lens = np.concatenate(
                [tail_len, tail_start[body_sel] + overlap]
            )
            range_owner = np.concatenate(
                [np.arange(n, dtype=np.int64), body_sel]
            )
            range_width = np.concatenate([
                bucket_widths(tail_len, max_bucket=cfg.block_len),
                np.full((len(body_sel),), cfg.block_len, np.int64),
            ])
            order = np.argsort(range_width, kind="stable")
            sorted_w = range_width[order]
            n_ranges = len(order)
            group_lo = (
                np.flatnonzero(np.r_[True, sorted_w[1:] != sorted_w[:-1]])
                if n_ranges
                else np.zeros((0,), np.int64)
            )

        def host_batches():
            # a generator: encode stays lazy, overlapping device dispatch
            # in both consumption modes below
            for g, lo in enumerate(group_lo):
                hi = group_lo[g + 1] if g + 1 < len(group_lo) else n_ranges
                idx = order[lo:hi]
                w = int(sorted_w[lo])
                with stages.timed("encode"):
                    r_starts = range_starts[idx]
                    r_lens = range_lens[idx]
                    enc = encode_blocks_ranges(
                        blob, r_starts, r_lens,
                        block_counts(r_lens, w, overlap), w, overlap,
                    )
                    if enc is None:  # no compiler: per-group Python slices
                        r_doc = range_owner[idx]
                        rel = r_starts - doc_off[r_doc]
                        enc = encode_blocks(
                            [
                                raw[d][s : s + ln]
                                for d, s, ln in zip(
                                    r_doc.tolist(), rel.tolist(),
                                    r_lens.tolist(),
                                )
                            ],
                            w,
                            overlap=overlap,
                        )
                    tok, blk_lens, owners_local = enc
                    owners = range_owner[idx].astype(np.int32)[owners_local]
                n_blocks = tok.shape[0]
                bs = _tile_bs(cfg, w)  # shared with prewarm's shape set
                # Greedy power-of-two row chunks: full bs tiles, then the
                # tail decomposes into descending power-of-two dispatches
                # (≥64; the last one zero-pads).  A width group with 33
                # leftover blocks must not dispatch (and compute!) a
                # 16384-row tile — measured 2.5× of the ragged regime's
                # device bytes were tail padding at 2k articles.  Chunks,
                # not one bucketed tail tile: every corpus then draws from
                # the SAME O(log bs) shape set per width, so one warm corpus
                # compiles (almost) everything — a per-corpus bucketed tail
                # would trickle fresh shapes (and recompiles) into every
                # corpus that follows.
                start = 0
                while start < n_blocks:
                    remaining = n_blocks - start
                    rows = bs
                    if remaining < bs:
                        rows = 64
                        while rows * 2 <= remaining:
                            rows *= 2
                    t = tok[start : start + rows]
                    l = blk_lens[start : start + rows]
                    o = owners[start : start + rows]
                    if t.shape[0] < rows:
                        pad = rows - t.shape[0]
                        t = np.concatenate([t, np.zeros((pad, w), np.uint8)])
                        l = np.concatenate([l, np.zeros((pad,), np.int32)])
                        o = np.concatenate([o, np.zeros((pad,), np.int32)])
                    yield (t, l, o)
                    start += rows

        return host_batches()

    def _accumulate_device(self, texts: Sequence[str | bytes], trace_id=None):
        """``(running, n_bucket, use_oph)``: the device-resident combined
        signature accumulator (RAW for the OPH backend — densify happens
        once downstream) after streaming every tile through the pipelined
        dispatch executor.

        The ragged corpus is grouped by power-of-two *width buckets* (a doc
        of 700 B rides a 1024-wide row, not a block_len-wide one) and docs
        longer than ``cfg.block_len`` split blockwise (:meth:`_host_tiles`,
        the shared chunker); every group folds into one running per-article
        minimum on device.  Three properties are load-bearing for
        throughput on an H2D-constrained link (the ragged regime is
        transfer-bound, not compute-bound — DESIGN.md §5):

        - bucketing cuts padded bytes on realistic length mixes vs
          one-width encoding, and padding that remains is zeros (cheap for
          a compressing transport);
        - each tile crosses the boundary as ONE packed ``device_put``
          (``ops/pack.py``) and ONE fused jitted dispatch with the
          accumulator donated (``ops.minhash.make_fused_tile_step``) —
          down from three serialized puts + two dispatches per tile
          (``cfg.packed_h2d=False`` restores that legacy transport, kept
          byte-identical for parity certification);
        - puts queue ahead of compute (async dispatch, no host sync until
          the caller materialises a result), and the
          encode→pack→put→dispatch stages run pipelined with a bounded
          in-flight window (``pipeline/dispatch.py``).

        Rows past ``len(texts)`` are untouched ⇒ all-``U32_MAX``.
        """
        cfg, params = self.cfg, self.params
        use_oph = cfg.backend == "oph"
        resolve_signature_fn(cfg.backend)  # validates the name up front

        import jax
        import jax.numpy as jnp

        from advanced_scrapper_tpu.core.mesh import maybe_enable_compile_cache

        maybe_enable_compile_cache()

        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.ops.minhash import accumulate_block_signatures
        from advanced_scrapper_tpu.ops.shingle import U32_MAX

        tid = trace_id or trace.new_trace_id()
        raw = [to_bytes(t) for t in texts]
        n = len(raw)
        # Bucket the article count so combine compiles O(log N) variants, not
        # one per corpus size (same trick as the block-length axis).
        n_bucket = bucket_len(n, min_bucket=64)
        host_batches = self._host_tiles(raw, trace_id=tid)

        # The tile plane rides the pipelined dispatch executor
        # (pipeline/dispatch.py): a pack stage draws width-group tiles off
        # the encode generator, a put pool (ASTPU_DEDUP_PUT_WORKERS; 0 =
        # transport auto — resolve_put_workers) overlaps H2D round trips,
        # and this thread drains the depth-N staged window and dispatches.
        # The min-combine is order-independent, so out-of-order arrival
        # from the pool never matters.
        from advanced_scrapper_tpu.obs import devprof
        from advanced_scrapper_tpu.pipeline.dispatch import PipelinedDispatcher

        put_workers = resolve_put_workers(cfg)
        packed_mode = cfg.packed_h2d
        probe = self.dispatch_probe

        if packed_mode:
            from advanced_scrapper_tpu.ops.pack import pack_tile

            step = self._get_fused_step()

            def pack(batch):
                t, l, o = batch
                with stages.timed("encode"):  # host memcpy: encode plane
                    return pack_tile(t, l, o), t.shape[0], t.shape[1]

            def put(item):
                buf, rows, w = item
                t0 = time.perf_counter()
                with stages.timed("h2d"):
                    dev = jax.device_put(buf)
                stages.count_device_put(buf.nbytes, "dedup")
                return dev, rows, w, buf.nbytes, time.perf_counter() - t0

            def dispatch(running, item):
                dev, rows, w, _nb, _pms = item
                # latency ledger: per-dispatch wall clock by kernel/shape
                # (async-submit timing; ASTPU_DISPATCH_TIMING=fenced
                # blocks until ready for ground truth)
                with devprof.dispatch_span(
                    "dedup_fused_tile", rows=rows, width=w, trace=tid
                ) as sp:
                    out = step(
                        running, dev, rows=rows, width=w, num_articles=n_bucket
                    )
                    sp.out = out
                # counted on success, INSIDE the fn: the OOM-backoff
                # ladder then ledgers exactly its leaf dispatches
                stages.count_dispatch("dedup")
                return out

            def split_packed(item):
                """Device-OOM halving: D2H the packed buffer, re-pack as
                two half-row tiles, re-put.  Each sub-item carries its
                TRUE row count — on an odd-row tile (non-power-of-two
                ``block_len`` configs) the halves differ by one row, and
                a mislabeled count would shift the trailer decode.  For
                the default power-of-two shapes the halves stay inside
                the prewarmed set (no recompile storm); odd shapes may
                compile a backoff variant once.  The extra puts/bytes
                land on the always-on device ledger like any transfer."""
                dev, rows, w, _nb, _pms = item
                buf = np.asarray(dev)
                tok = buf[: rows * w].reshape(rows, w)
                trailer = buf[rows * w :].view("<i4").reshape(2, rows)
                half = rows // 2
                out = []
                for lo, hi in ((0, half), (half, rows)):
                    sl = slice(lo, hi)
                    pb = pack_tile(tok[sl], trailer[0, sl], trailer[1, sl])
                    with stages.timed("h2d"):
                        d = jax.device_put(pb)
                    stages.count_device_put(pb.nbytes, "dedup")
                    out.append((d, hi - lo, w, pb.nbytes, 0.0))
                return out
        else:
            # legacy tile transport (parity certification / escape hatch):
            # three serialized puts + two dispatches per tile, same bytes
            block_fn = resolve_signature_fn(cfg.backend)
            if use_oph:
                from advanced_scrapper_tpu.ops.oph import oph_raw_signatures

                block_fn = oph_raw_signatures  # densify AFTER the combine

            def pack(batch):
                t, l, o = batch
                return t, l, o, t.nbytes + l.nbytes + o.nbytes

            def put(item):
                t, l, o, nbytes = item
                t0 = time.perf_counter()
                with stages.timed("h2d"):
                    t, l, o = (
                        jax.device_put(t), jax.device_put(l), jax.device_put(o)
                    )
                for arr in (t, l, o):
                    stages.count_device_put(arr.nbytes, "dedup")
                return t, l, o, nbytes, time.perf_counter() - t0

            def dispatch(running, item):
                t, l, o, _nb, _pms = item
                stages.count_dispatch("dedup")  # block_fn; the fold below
                with devprof.dispatch_span(
                    "dedup_legacy_tile",
                    rows=int(t.shape[0]), width=int(t.shape[1]), trace=tid,
                ) as sp:
                    out = accumulate_block_signatures(
                        running, block_fn(t, l, params), o,
                        num_articles=n_bucket,
                    )
                    sp.out = out
                return out

        running = jnp.full((n_bucket, params.num_perm), U32_MAX, jnp.uint32)
        dispatched = 0
        window = cfg.dispatch_window
        if self.ladder is not None and self.ladder.active("shrink_window"):
            # brownout step 1: halve the in-flight dispatch window —
            # less resident device memory, more backpressure upstream
            from advanced_scrapper_tpu.pipeline.dispatch import (
                resolve_dispatch_window,
            )

            window = max(
                1, resolve_dispatch_window(cfg.dispatch_window, put_workers) // 2
            )
            self.ladder.count_effect("shrink_window")
        pipe = PipelinedDispatcher(
            host_batches,
            pack=pack,
            put=put,
            put_workers=put_workers,
            window=window,
        )
        from advanced_scrapper_tpu.pipeline.dispatch import (
            dispatch_with_oom_backoff,
        )

        try:
            for item in pipe:
                rows = int(item[0].shape[0]) if not packed_mode else item[1]
                t0 = time.perf_counter()
                with stages.timed("kernel"), self.step_timer.step(rows):
                    # async dispatch; device waits land here
                    if packed_mode:
                        # RESOURCE_EXHAUSTED halves the tile (re-pack,
                        # re-put, retry — byte-identical fold) down to
                        # the 64-row floor, then fails cleanly
                        running = dispatch_with_oom_backoff(
                            dispatch, running, item,
                            split=split_packed,
                            rows_of=lambda it: it[1],
                        )
                    else:
                        running = dispatch(running, item)
                if not packed_mode:
                    stages.count_dispatch("dedup")
                if probe is not None:
                    probe(
                        {
                            "tile": dispatched,
                            "rows": rows,
                            "width": int(
                                item[2] if packed_mode else item[0].shape[1]
                            ),
                            "h2d_bytes": int(item[-2]),
                            "put_ms": round(item[-1] * 1e3, 3),
                            "dispatch_ms": round(
                                (time.perf_counter() - t0) * 1e3, 3
                            ),
                        }
                    )
                dispatched += 1
        finally:
            pipe.close()
        self._m_batches.inc(dispatched)
        self.last_tiles = dispatched
        if trace.RECORDER.active:
            trace.record(
                "span", "dedup.dispatch", trace=tid, batches=dispatched, docs=n
            )
        return running, n_bucket, use_oph

    def _fine_salt(self) -> np.ndarray:
        """``subband_salt(cand_subbands)`` (validated against num_perm) or
        a zero-length array — the fused epilogues select the fine-band
        variant by its static shape."""
        cs = self.cfg.cand_subbands
        if not cs:
            return np.zeros((0,), np.uint32)
        if self.params.num_perm % cs:
            raise ValueError(
                f"cand_subbands {cs} must divide num_perm "
                f"{self.params.num_perm} (each sub-band folds "
                "num_perm/cand_subbands signature rows)"
            )
        from advanced_scrapper_tpu.ops.lsh import subband_salt

        return subband_salt(cs)

    def _valid_device(self, raw: list, n_bucket: int):
        """Device ``bool[n_bucket]`` shingle-eligibility mask (counted as
        the one per-corpus put the epilogue needs beside the tiles)."""
        import jax

        from advanced_scrapper_tpu.obs import stages

        n = len(raw)
        lens = np.fromiter((len(r) for r in raw), np.int64, count=n)
        valid = np.zeros((n_bucket,), bool)
        valid[:n] = lens >= self.params.shingle_k
        dev = jax.device_put(valid)
        stages.count_device_put(valid.nbytes, "dedup")
        return dev

    def _prepare(self, texts: Sequence[str | bytes]):
        """Shared front half of both resolution paths: encode → device
        signature accumulator → ONE fused epilogue dispatch (OPH densify +
        coarse/fine candidate keys + per-band candidates), so a full
        corpus is ``tiles × 1`` dispatches plus this epilogue before
        resolution."""
        from advanced_scrapper_tpu.obs import stages, trace

        fused_candidate_epilogue = _lsh_epilogue("fused_candidate_epilogue")
        tid = trace.new_trace_id()
        n = len(texts)
        raw = [to_bytes(t) for t in texts]  # encode once; identity on bytes
        running, n_bucket, use_oph = self._accumulate_device(
            raw, trace_id=tid
        )
        valid = self._valid_device(raw, n_bucket)
        with stages.timed("resolve"), trace.span(
            "dedup.candidates", trace=tid, docs=n
        ):
            sigs, keys, rep_bands = fused_candidate_epilogue(
                running,
                valid,
                np.asarray(self.params.band_salt),
                self._fine_salt(),
                densify_oph=use_oph,
            )
            stages.count_dispatch("dedup")
        self._rerank_applied = False
        if self.rerank_hook is not None:
            if self.ladder is not None and self.ladder.active("skip_rerank"):
                # brownout step 2: the precision tier is bypassed under
                # sustained pressure — candidates pass through unreranked
                # (counted; reverses the moment the step exits)
                self.ladder.count_effect("skip_rerank")
            else:
                # the declared RERANK_HOOK_EDGE: candidates flow through
                # the rerank tier before EITHER resolution path sees them
                with trace.span("dedup.rerank", trace=tid, docs=n):
                    rep_bands = self.rerank_hook(raw, sigs, rep_bands, valid)
                self._rerank_applied = bool(
                    getattr(self.rerank_hook, "authoritative", False)
                )
        return raw, sigs, keys, valid, rep_bands, n_bucket, tid

    def dedup_reps_async(self, texts: Sequence[str | bytes], *, _regime: str = "async"):
        """Dispatch the full dedup and return the DEVICE ``int32[bucket]``
        rep array without syncing — everything from encode to resolve is
        async, so a caller streaming multiple corpora overlaps corpus i+1's
        H2D/compute with corpus i's readback (the production firehose
        regime; one-shot callers use :meth:`dedup_reps`).  Rows past
        ``len(texts)`` are padding (invalid ⇒ self-assigned).

        This path never syncs, so borderline edges are handled by the
        estimator-only ``fine_margin`` bar — the exact-Jaccard
        confirmation stage needs a host round trip and lives in the
        one-shot :meth:`dedup_reps` (measured trade in DESIGN.md §2e).
        """
        # Device-resident end to end: combined signatures never round-trip to
        # the host (the sig D2H + re-H2D bounce cost ~0.3 s per 8k articles
        # on the tunneled link); the only D2H is the final int32[N] reps.
        from advanced_scrapper_tpu.obs import stages, trace

        if self.rerank_hook is not None:
            # the declared RERANK_HOOK_EDGE needs the candidate matrix at
            # the host boundary → the two-stage split (one extra dispatch)
            _raw, sigs, keys, valid, rep_bands, n_bucket, tid = self._prepare(
                texts
            )
            self._m_docs[_regime].inc(len(texts))
            with stages.timed("resolve"), trace.span(
                "dedup.resolve", trace=tid, regime=_regime, docs=len(texts)
            ):
                if self._rerank_applied:
                    # an authoritative tier rewrote the matrix: its cells
                    # are settled TRUE-Jaccard cluster edges, already
                    # exact-verified where it mattered — re-screening them
                    # by estimator agreement would re-drop precisely the
                    # true pairs whose signatures underestimate (the tier
                    # keeps them on settled evidence), so resolve trusts
                    # every non-self cell
                    rb_host = np.asarray(rep_bands)
                    ok = rb_host != np.arange(
                        rb_host.shape[0], dtype=rb_host.dtype
                    )[:, None]
                    rep = resolve_rep_bands_from_ok(
                        rep_bands, ok, valid,
                        jump_rounds=_jump_rounds(n_bucket),
                    )
                    stages.count_dispatch("dedup")
                    return rep
                if self.cfg.cand_subbands and self.cfg.fine_margin:
                    thr = fine_edge_thresholds(
                        rep_bands,
                        keys,
                        self.cfg.sim_threshold,
                        self.cfg.fine_margin,
                        num_coarse=self.params.num_bands,
                    )
                    stages.count_dispatch("dedup")
                else:
                    thr = self.cfg.sim_threshold
                rep = resolve_rep_bands(
                    rep_bands, sigs, valid, thr,
                    jump_rounds=_jump_rounds(n_bucket),
                )
                stages.count_dispatch("dedup")
                return rep
        # no hook: the WHOLE resolution is one fused dispatch — a full
        # corpus is tiles × 1 dispatches plus this epilogue
        fused_resolve_epilogue = _lsh_epilogue("fused_resolve_epilogue")

        tid = trace.new_trace_id()
        raw = [to_bytes(t) for t in texts]
        running, n_bucket, use_oph = self._accumulate_device(
            raw, trace_id=tid
        )
        valid = self._valid_device(raw, n_bucket)
        # _regime: the one-shot API's estimator-only branch delegates here —
        # its documents must count as "oneshot", not inflate the async series
        self._m_docs[_regime].inc(len(texts))
        with stages.timed("resolve"), trace.span(
            "dedup.resolve", trace=tid, regime=_regime, docs=len(texts)
        ):
            rep = fused_resolve_epilogue(
                running,
                valid,
                np.asarray(self.params.band_salt),
                self._fine_salt(),
                self.cfg.sim_threshold,
                self.cfg.fine_margin,
                densify_oph=use_oph,
                num_coarse=self.params.num_bands,
                jump_rounds=_jump_rounds(n_bucket),
                use_fine_margin=bool(
                    self.cfg.cand_subbands and self.cfg.fine_margin
                ),
            )
            stages.count_dispatch("dedup")
            return rep

    # -- mesh-sharded packed plane (pod-scale dedup) ---------------------------

    def _get_sharded_fused_step(self, mesh):
        """The mesh's shard_map'd single-dispatch tile step (params
        constant-folded, accumulator donated per shard) — cached per
        mesh; jit then caches per static (rows, width, num_articles),
        the same shape set :meth:`prewarm_sharded` compiles."""
        key = (mesh, "fused")
        step = self._sharded_steps.get(key)
        if step is None:
            from advanced_scrapper_tpu.obs import devprof
            from advanced_scrapper_tpu.parallel.sharded_packed import (
                make_sharded_fused_tile_step,
            )

            step = devprof.instrument_jit(
                make_sharded_fused_tile_step(
                    mesh, self.params, self.cfg.backend
                ),
                "sharded_fused_tile",
            )
            self._sharded_steps[key] = step
        return step

    def _get_sharded_init(self, mesh):
        key = (mesh, "init")
        init = self._sharded_steps.get(key)
        if init is None:
            from advanced_scrapper_tpu.parallel.sharded_packed import (
                make_sharded_accumulator_init,
            )

            init = make_sharded_accumulator_init(mesh, self.params.num_perm)
            self._sharded_steps[key] = init
        return init

    def _get_sharded_epilogue(self, mesh):
        """The end-of-corpus combine+resolve dispatch (``pmin`` across
        shards, then the async path's estimator-only resolution)."""
        key = (mesh, "resolve")
        epi = self._sharded_steps.get(key)
        if epi is None:
            from advanced_scrapper_tpu.parallel.sharded_packed import (
                make_sharded_resolve_epilogue,
            )

            epi = make_sharded_resolve_epilogue(
                mesh,
                self.params,
                threshold=self.cfg.sim_threshold,
                fine_margin=self.cfg.fine_margin,
                fine_salt=self._fine_salt(),
                backend=self.cfg.backend,
            )
            self._sharded_steps[key] = epi
        return epi

    def _get_sharded_keys_epilogue(self, mesh):
        key = (mesh, "keys")
        epi = self._sharded_steps.get(key)
        if epi is None:
            from advanced_scrapper_tpu.parallel.sharded_packed import (
                make_sharded_keys_epilogue,
            )

            epi = make_sharded_keys_epilogue(mesh, self.params, self.cfg.backend)
            self._sharded_steps[key] = epi
        return epi

    def _sharded_tile_groups(self, tiles, nsh: int):
        """Group the shared chunker's same-shape tiles into per-shard
        groups of ``nsh`` — one group = one partitioned dispatch, each
        shard owning one tile.  The min-combine is order- and
        placement-independent, so which shard folds which tile never
        shows in the output.  A shape's leftover group pads with zero
        tiles (lens 0 ⇒ all-``U32_MAX`` signatures, the min identity —
        exactly how in-tile padding rows already behave), so every
        shard's ledger stays uniform: tiles + 1 puts, tiles + 1
        dispatches per corpus, per shard."""
        pending: dict = {}
        for t, l, o in tiles:
            shape = (t.shape[0], t.shape[1])
            bucket = pending.setdefault(shape, [])
            bucket.append((t, l, o))
            if len(bucket) == nsh:
                yield shape, pending.pop(shape)
        for (rows, w), bucket in list(pending.items()):
            while len(bucket) < nsh:
                bucket.append(
                    (
                        np.zeros((rows, w), np.uint8),
                        np.zeros((rows,), np.int32),
                        np.zeros((rows,), np.int32),
                    )
                )
            yield (rows, w), bucket

    def _accumulate_device_sharded(self, raw: list, mesh, trace_id=None):
        """``(running, n_bucket, use_oph)`` — the sharded twin of
        :meth:`_accumulate_device`: the same shared chunker feeds the
        same pipelined executor (``pipeline/dispatch.py``, a sharded
        source on the one graph), but each tile group crosses H2D as one
        packed ``device_put`` PER SHARD (this host puts its local shards
        only) assembled into a global dim-0-sharded buffer — zero-copy —
        and dispatches as ONE partitioned fused step that folds every
        shard's tile into its own DONATED accumulator row.  Per-shard
        ledger (``shard=`` label on the always-on device counters):
        exactly tiles + 1 puts and tiles + 1 dispatches per corpus, the
        single-device plane's contract applied at pod scale.  ``raw``
        is the already-``to_bytes``-converted corpus (both callers
        convert once at their boundary)."""
        cfg, params = self.cfg, self.params
        use_oph = cfg.backend == "oph"
        resolve_signature_fn(cfg.backend)  # validates the name up front

        import jax

        from advanced_scrapper_tpu.core.mesh import maybe_enable_compile_cache

        maybe_enable_compile_cache()

        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.ops.pack import pack_tile
        from advanced_scrapper_tpu.parallel.sharded_packed import (
            assemble_packed_tiles,
            local_shard_rows,
            mesh_num_shards,
            shard_row_devices,
        )
        from advanced_scrapper_tpu.obs import devprof
        from advanced_scrapper_tpu.pipeline.dispatch import PipelinedDispatcher

        tid = trace_id or trace.new_trace_id()
        n = len(raw)
        n_bucket = bucket_len(n, min_bucket=64)
        nsh = mesh_num_shards(mesh)
        devices = shard_row_devices(mesh)
        local_rows = local_shard_rows(mesh)
        step = self._get_sharded_fused_step(mesh)
        tiles = self._sharded_tile_groups(self._host_tiles(raw, trace_id=tid), nsh)

        from advanced_scrapper_tpu.ops.pack import packed_nbytes

        def pack(group):
            (rows, w), batch = group
            with stages.timed("encode"):  # host memcpy: encode plane
                # LOCAL shards only: a remote shard's tile is packed (and
                # put) by the host that owns it — packing all n_shards
                # here would burn encode-plane memcpy on buffers this
                # host immediately discards
                bufs = {s: pack_tile(*batch[s]) for s in local_rows}
            return bufs, rows, w

        def put(item):
            bufs, rows, w = item
            t0 = time.perf_counter()
            nb = packed_nbytes(rows, w)  # uniform across shards
            with stages.timed("h2d"):
                shards = []
                for s in local_rows:
                    # one put per shard per tile, onto the device that
                    # owns that accumulator row (shard_row_devices —
                    # derived from the sharding's index map)
                    shards.append(jax.device_put(bufs[s][None], devices[s]))
                    stages.count_device_put(
                        bufs[s].nbytes, "sharded", shard=s
                    )
                packed = assemble_packed_tiles(mesh, shards, nb)
            nbytes = sum(bufs[s].nbytes for s in local_rows)
            return packed, rows, w, nbytes, time.perf_counter() - t0

        def dispatch(running, item):
            packed, rows, w, _nb, _pms = item
            # ONE latency observation per partitioned launch (labeling it
            # per shard would count the same wall clock nsh times); the
            # per-shard truth lives in the put/dispatch count ledger below
            with devprof.dispatch_span(
                "sharded_fused_tile", rows=rows, width=w, trace=tid
            ) as sp:
                out = step(
                    running, packed, rows=rows, width=w, num_articles=n_bucket
                )
                sp.out = out
            # one partitioned launch = one execution per shard
            for s in local_rows:
                stages.count_dispatch("sharded", shard=s)
            return out

        running = self._get_sharded_init(mesh)(num_articles=n_bucket)
        probe = self.dispatch_probe
        pipe = PipelinedDispatcher(
            tiles,
            pack=pack,
            put=put,
            put_workers=resolve_put_workers(cfg),
            window=cfg.dispatch_window,
            name="dedup.sharded.h2d",
        )
        dispatched = 0
        try:
            for item in pipe:
                t0 = time.perf_counter()
                rows = int(item[1])
                with stages.timed("kernel"), self.step_timer.step(rows * nsh):
                    running = dispatch(running, item)
                if probe is not None:
                    probe(
                        {
                            "tile": dispatched,
                            "rows": rows,
                            "width": int(item[2]),
                            "shards": nsh,
                            "h2d_bytes": int(item[3]),
                            "put_ms": round(item[4] * 1e3, 3),
                            "dispatch_ms": round(
                                (time.perf_counter() - t0) * 1e3, 3
                            ),
                        }
                    )
                dispatched += 1
        finally:
            pipe.close()
        self._m_batches.inc(dispatched)
        self.last_tiles = dispatched
        if trace.RECORDER.active:
            trace.record(
                "span", "dedup.dispatch", trace=tid,
                batches=dispatched, docs=n, shards=nsh,
            )
        return running, n_bucket, use_oph

    def _valid_device_sharded(self, raw: list, n_bucket: int, mesh):
        """Replicated device ``bool[n_bucket]`` eligibility mask — the
        sharded twin of :meth:`_valid_device` (one replica lands on every
        shard, so the ledger counts one put per shard)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from advanced_scrapper_tpu.obs import stages
        from advanced_scrapper_tpu.parallel.sharded_packed import (
            local_shard_rows,
        )

        n = len(raw)
        lens = np.fromiter((len(r) for r in raw), np.int64, count=n)
        valid = np.zeros((n_bucket,), bool)
        valid[:n] = lens >= self.params.shingle_k
        dev = jax.device_put(valid, NamedSharding(mesh, P(None)))
        for s in local_shard_rows(mesh):
            stages.count_device_put(valid.nbytes, "sharded", shard=s)
        return dev

    def prewarm_sharded(self, mesh, n_articles: int | None = None) -> int:
        """Compile the sharded packed plane's (mesh, bucket, rows) shape
        set ahead of the first corpus — the sharded twin of
        :meth:`prewarm`, drawing from the SAME derivation
        (``_prewarm_widths`` × ``_tile_bs``/``_tile_rows_options``) the
        shared chunker emits, so the two shape sets cannot silently
        disjoint (the PR 9 lesson, jit-cache-asserted in tier-1).  Also
        compiles the end-of-corpus resolve epilogue for the bucket.
        With ``ASTPU_COMPILE_CACHE`` set the compiles persist across
        processes.  Returns the number of shape variants compiled."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from advanced_scrapper_tpu.core.mesh import maybe_enable_compile_cache
        from advanced_scrapper_tpu.ops.pack import packed_nbytes
        from advanced_scrapper_tpu.parallel.sharded_packed import (
            assemble_packed_tiles,
            local_shard_rows,
            shard_row_devices,
        )

        maybe_enable_compile_cache()
        cfg = self.cfg
        n_bucket = bucket_len(
            n_articles if n_articles else cfg.batch_size, min_bucket=64
        )
        step = self._get_sharded_fused_step(mesh)
        init = self._get_sharded_init(mesh)
        devices = shard_row_devices(mesh)
        local_rows = local_shard_rows(mesh)
        compiled = 0
        for w in _prewarm_widths(cfg):
            for rows in _tile_rows_options(_tile_bs(cfg, w)):
                running = init(num_articles=n_bucket)
                nb = packed_nbytes(rows, w)
                zeros = np.zeros((1, nb), np.uint8)
                shards = [
                    jax.device_put(zeros, devices[s]) for s in local_rows
                ]
                packed = assemble_packed_tiles(mesh, shards, nb)
                step(
                    running, packed, rows=rows, width=w, num_articles=n_bucket
                ).block_until_ready()
                compiled += 1
        # the per-bucket epilogue (combine + resolve) compiles here too,
        # so the first corpus pays zero compiles end to end
        running = init(num_articles=n_bucket)
        valid = jax.device_put(
            np.zeros((n_bucket,), bool), NamedSharding(mesh, P(None))
        )
        self._get_sharded_epilogue(mesh)(
            running, valid, jump_rounds=_jump_rounds(n_bucket)
        ).block_until_ready()
        compiled += 1
        return compiled

    def dedup_reps_sharded(self, texts: Sequence[str | bytes], mesh) -> np.ndarray:
        """int32[N] representatives over a device mesh — the pod-scale
        twin of :meth:`dedup_reps_async`'s estimator-only resolution
        (byte-identical, parity-tested; use the one-shot
        :meth:`dedup_reps` when the exact-verify precision path is
        required).

        Default (``cfg.packed_h2d``): the PACKED plane — the shared
        width-bucketed chunker feeds per-shard packed single-put tiles
        through the pipelined executor into one partitioned fused
        donated dispatch per tile group (1 put + 1 dispatch per tile per
        shard, shard-labelled on the always-on ledger), with the
        cross-shard ``pmin`` combine + LSH resolution as one end-of-corpus
        epilogue dispatch.  ``ASTPU_DEDUP_PACKED_H2D=0`` restores the
        legacy unpacked transport (blockwise ``encode_blocks`` →
        ``make_sharded_block_dedup``), kept byte-identical as the parity
        oracle."""
        if self.cfg.packed_h2d:
            return self._dedup_reps_sharded_packed(texts, mesh)
        return self._dedup_reps_sharded_legacy(texts, mesh)

    def _dedup_reps_sharded_packed(self, texts, mesh) -> np.ndarray:
        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.parallel.sharded_packed import (
            local_shard_rows,
        )

        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        tid = trace.new_trace_id()
        self._m_docs["sharded"].inc(n)
        raw = [to_bytes(t) for t in texts]
        running, n_bucket, _use_oph = self._accumulate_device_sharded(
            raw, mesh, trace_id=tid
        )
        valid = self._valid_device_sharded(raw, n_bucket, mesh)
        epi = self._get_sharded_epilogue(mesh)
        with stages.timed("resolve"), trace.span(
            "dedup.resolve", trace=tid, regime="sharded", docs=n
        ):
            rep = epi(running, valid, jump_rounds=_jump_rounds(n_bucket))
            for s in local_shard_rows(mesh):
                stages.count_dispatch("sharded", shard=s)
            out = np.asarray(rep)[:n]
        self._count_result("sharded", n, out)
        return out

    def _keys_wide_sharded(self, raw: list, mesh) -> np.ndarray:
        """Host ``uint32[N, nb, 2]`` wide band keys off the mesh-sharded
        packed accumulator — the sharded twin of
        ``signatures_and_keys(wide=True, sync_sigs=False)``: one keys
        epilogue dispatch (``pmin`` combine + ``band_keys_wide``),
        replicated, signatures never synced."""
        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.parallel.sharded_packed import (
            local_shard_rows,
        )

        tid = trace.new_trace_id()
        running, _n_bucket, _use_oph = self._accumulate_device_sharded(
            raw, mesh, trace_id=tid
        )
        keys_dev = self._get_sharded_keys_epilogue(mesh)(running)
        for s in local_shard_rows(mesh):
            stages.count_dispatch("sharded", shard=s)
        with stages.timed("kernel"), trace.span(
            "dedup.readback", trace=tid, docs=len(raw)
        ):  # readback sync: the device drains here
            return np.asarray(keys_dev)[: len(raw)]

    def _dedup_reps_sharded_legacy(self, texts, mesh) -> np.ndarray:
        """The PR 2 unpacked sharded transport — blockwise encode →
        ``make_sharded_block_dedup`` (three arrays H2D, one monolithic
        dispatch).  Kept byte-identical behind ``ASTPU_DEDUP_PACKED_H2D=0``
        as the packed plane's parity oracle (MIGRATION: new callers use
        the packed entry)."""
        from advanced_scrapper_tpu.obs import stages, trace
        from advanced_scrapper_tpu.parallel.sharded import (
            make_sharded_block_dedup,
        )

        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        tid = trace.new_trace_id()
        self._m_docs["sharded"].inc(n)
        cfg = self.cfg
        raw = [to_bytes(t) for t in texts]
        with stages.timed("encode"):
            tok, lens, owners = encode_blocks(
                raw, cfg.block_len, overlap=self.params.shingle_k - 1
            )
            owners = owners.astype(np.int32)
            n_bucket = bucket_len(n, min_bucket=64)
            # shard divisibility + bucketed block axis: pad rows to the
            # scratch article slot (owner n_bucket → sliced off on device)
            ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
            rows = bucket_len(max(tok.shape[0], ndev), min_bucket=64)
            rows = -(-rows // ndev) * ndev  # exact multiple for odd meshes
            if tok.shape[0] < rows:
                pad = rows - tok.shape[0]
                tok = np.concatenate(
                    [tok, np.zeros((pad, cfg.block_len), np.uint8)]
                )
                lens = np.concatenate([lens, np.zeros((pad,), np.int32)])
                owners = np.concatenate(
                    [owners, np.full((pad,), n_bucket, np.int32)]
                )
        key = (mesh, n_bucket, cfg.block_len)
        step = self._sharded_steps.get(key)
        if step is None:
            step = make_sharded_block_dedup(
                mesh,
                self.params,
                n_bucket,
                threshold=cfg.sim_threshold,
                jump_rounds=_jump_rounds(n_bucket),
                backend=cfg.backend,
                cand_subbands=cfg.cand_subbands,
                fine_margin=cfg.fine_margin,
            )
            self._sharded_steps[key] = step
        with self.step_timer.step(int(tok.shape[0])):
            rep, _hist = step(tok, lens, owners)
        stages.count_dispatch("dedup")
        self._m_batches.inc()
        with stages.timed("resolve"), trace.span(
            "dedup.resolve", trace=tid, regime="sharded", docs=n
        ):
            out = np.asarray(rep)[:n]
        self._count_result("sharded", n, out)
        return out

    def _exact_verified_ok(self, raw, sigs, keys, valid, rep_bands):
        """Verified-edge matrix with statistically fragile edges confirmed
        (or killed) by EXACT shingle-set Jaccard.

        The estimator cannot meet the precision budget alone: at 128 perms
        its σ≈0.04, and the borderline band [0.70, 0.72) holds both the
        false merges (true J < 0.7, the r4 ~3.2-point precision giveback)
        and the genuine bridges that recover cross-estimator disagreement
        recall (measured frontier: tools/sweep_fine_margin.py).  Exact
        Jaccard — the oracle's own ``shingle_set``/``jaccard`` definition,
        imported so the two can never diverge — separates them perfectly,
        and the flagged set is tiny (~130 pairs per 2048 docs), so the
        host cost is noise in the one-shot path.  Returns the device
        ``ok`` matrix (agreement pass runs ONCE) with refuted edges
        cleared, ready for ``resolve_rep_bands_from_ok``.
        """
        from advanced_scrapper_tpu.cpu.oracle import jaccard, shingle_set
        from advanced_scrapper_tpu.obs import stages

        need_dev, ok_dev = borderline_edge_mask(
            rep_bands,
            sigs,
            keys,
            valid,
            self.cfg.sim_threshold,
            self.cfg.exact_verify_band,
            num_coarse=self.params.num_bands,
        )
        stages.count_dispatch("dedup")
        from advanced_scrapper_tpu.obs.telemetry import NOOP

        need = np.asarray(need_dev)
        # decision provenance: which pairs the margin tier re-settled
        # (exact Jaccard, or the strict estimator bar past the cap) —
        # consumed by _emit_decisions when attributing verdict tiers
        self._last_exact_pairs = {}
        if self._m_cand is not NOOP:
            # metric-only host work (skipped when telemetry is disabled),
            # counted BEFORE the borderline early-return: candidate volume
            # must not read 0 just because every edge cleared the bar
            rb_m = np.asarray(rep_bands)
            self._m_cand.inc(
                int((rb_m != np.arange(rb_m.shape[0])[:, None]).sum())
            )
            self._m_borderline.inc(int(need.sum()))
        if not need.any():
            return ok_dev
        rb = np.asarray(rep_bands)
        ok = np.asarray(ok_dev).copy()
        pairs = {}  # (lo, hi) -> verdict; an edge is undirected
        shingles: dict[int, set] = {}

        def sset(i: int) -> set:
            if i not in shingles:
                shingles[i] = shingle_set(raw[i], self.params.shingle_k)
            return shingles[i]

        checked = 0
        sigs_np = None
        for r, c in zip(*np.nonzero(need)):
            j = int(rb[r, c])
            key = (min(int(r), j), max(int(r), j))
            if key not in pairs:
                if checked >= self.cfg.exact_verify_cap:
                    # past the cap (pathological all-borderline corpora)
                    # the edge keeps an ESTIMATOR verdict — but at the
                    # strict fine-only bar (base + fine_margin) the
                    # estimator-only paths apply, not plain base: the
                    # certified path must never verify a flagged edge
                    # more laxly than the uncertified ones do
                    if sigs_np is None:
                        sigs_np = np.asarray(sigs)
                    agree = float(
                        (sigs_np[key[0]] == sigs_np[key[1]]).mean()
                    )
                    pairs[key] = agree >= (
                        self.cfg.sim_threshold + self.cfg.fine_margin
                    )
                else:
                    checked += 1
                    pairs[key] = (
                        jaccard(sset(key[0]), sset(key[1]))
                        >= self.cfg.sim_threshold
                    )
            if not pairs[key]:
                ok[r, c] = False  # exact Jaccard (or strict bar) refuted it
        self._m_exact_checks.inc(checked)
        self._last_exact_pairs = pairs
        return ok

    def _emit_decisions(self, regime: str, out, keys_dev, n: int) -> None:
        """Decision-provenance emission for the certified one-shot path:
        per-verdict tier counters always, journal rows (with the winning
        band key) only when the journal is enabled — the keys D2H sync is
        gated on it, so the disabled journal costs zero extra transfers.

        Tier attribution joins the resolve output against the settling
        evidence the tiers left behind: the rerank hook's
        ``last_provenance`` (host-resettled pairs → margin/reprobe,
        everything else the device sketch settled → rerank, evicted
        members → rerank uniques), or — hookless — the margin stage's
        ``_last_exact_pairs``.  A doc with no settling evidence was
        decided by raw band collision geometry ("band").  The async path
        deliberately never emits: it never syncs verdicts to host, and a
        provenance sync would break that contract — streaming callers get
        provenance from the index path instead.
        """
        from advanced_scrapper_tpu.obs.decisions import get_recorder

        rec = get_recorder()
        out = np.asarray(out)[:n]
        dup = out != np.arange(n)
        if self._rerank_applied:
            prov = getattr(self.rerank_hook, "last_provenance", None) or {}
            evicted = getattr(self.rerank_hook, "last_evicted", None) or set()
            participants = getattr(
                self.rerank_hook, "last_participants", None
            ) or set()
            # strongest host evidence per doc: reprobe > margin
            host_tier: dict[int, str] = {}
            for (a, b), t in prov.items():
                if t in ("margin", "reprobe"):
                    for d in (a, b):
                        if t == "reprobe" or d not in host_tier:
                            host_tier[d] = t

            def dup_tier(i: int, r: int) -> str:
                key = (i, r) if i < r else (r, i)
                return prov.get(key, "rerank")

            def uniq_tier(i: int) -> str:
                if i in evicted:
                    return "rerank"
                t = host_tier.get(i)
                if t is not None:
                    return t
                return "rerank" if i in participants else "band"
        else:
            pairs = getattr(self, "_last_exact_pairs", None) or {}
            margin_docs = {d for k in pairs for d in k}

            def dup_tier(i: int, r: int) -> str:
                key = (i, r) if i < r else (r, i)
                return "margin" if key in pairs else "band"

            def uniq_tier(i: int) -> str:
                return "margin" if i in margin_docs else "band"

        tiers = [
            dup_tier(i, int(out[i])) if dup[i] else uniq_tier(i)
            for i in range(n)
        ]
        counts: dict[tuple[str, bool], int] = {}
        for i, t in enumerate(tiers):
            k = (t, bool(dup[i]))
            counts[k] = counts.get(k, 0) + 1
        for (t, is_dup), c in counts.items():
            rec.count(t, "dup" if is_dup else "unique", c)
        if rec.journal is None:
            return
        keys = np.asarray(keys_dev)[:n]  # journal-gated D2H sync
        rows = []
        for i in range(n):
            r = int(out[i])
            band_key = None
            if dup[i]:
                # winning band: the first candidate column where this
                # doc's key collides with its representative's (None for
                # purely transitive merges)
                cols = np.flatnonzero(keys[i] == keys[r])
                if cols.size:
                    band_key = int(keys[i, cols[0]])
            rows.append(
                {
                    "doc": i,
                    "verdict": "dup" if dup[i] else "unique",
                    "tier": tiers[i],
                    "attr": r if dup[i] else -1,
                    "band_key": band_key,
                    "regime": regime,
                }
            )
        rec.journal_rows(rows)

    def _emit_index_decisions(self, out, keys64, eligible, index) -> None:
        """Decision provenance for the streaming-index path: every
        eligible row's verdict settled at tier "index" (a persistent
        posting hit, or a fresh post).  When the journal is enabled, dup
        rows' winning band keys come from a per-key re-probe of their own
        (already-posted) keys: the column whose per-key attribution
        equals the row's answer is the colliding band — works for
        cross-run and intra-batch attributions alike, no index API
        change, and runs only when journaling."""
        from advanced_scrapper_tpu.obs.decisions import get_recorder

        rec = get_recorder()
        out = np.asarray(out)
        dup_rows = np.flatnonzero(out >= 0)
        n_dup = int(dup_rows.size)
        rec.count("index", "dup", n_dup)
        rec.count("index", "unique", int(eligible.sum()) - n_dup)
        if rec.journal is None:
            return
        k2 = keys64 if keys64.ndim == 2 else keys64.reshape(out.shape[0], -1)
        band_keys: dict[int, int | None] = {}
        if n_dup:
            nb = k2.shape[1]
            attr = np.asarray(
                index.probe_batch(k2[dup_rows].reshape(-1))
            ).reshape(n_dup, nb)
            for x, i in enumerate(dup_rows.tolist()):
                cols = np.flatnonzero(attr[x] == out[i])
                band_keys[i] = int(k2[i, cols[0]]) if cols.size else None
        rows = [
            {
                "doc": int(i),
                "verdict": "dup" if out[i] >= 0 else "unique",
                "tier": "index",
                "attr": int(out[i]),
                "band_key": band_keys.get(int(i)),
                "regime": "stream",
            }
            for i in np.flatnonzero(eligible).tolist()
        ]
        rec.journal_rows(rows)

    def dedup_reps(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """int32[N] first-seen-wins representative per text (union-find
        roots), with exact-Jaccard confirmation of statistically fragile
        edges (``exact_verify_band``) — the certified precision path."""
        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        from advanced_scrapper_tpu.obs import trace

        # exact verification is independent of fine-band candidacy:
        # coarse-borderline edges need confirmation even at cand_subbands=0
        # (borderline_edge_mask handles the no-fine-columns case)
        if not self.cfg.exact_verify_band:
            out = np.asarray(self.dedup_reps_async(texts, _regime="oneshot"))[:n]
            self._count_result("oneshot", n, out)
            return out
        from advanced_scrapper_tpu.obs import stages

        raw, sigs, keys, valid, rep_bands, n_bucket, tid = self._prepare(texts)
        self._m_docs["oneshot"].inc(n)
        with trace.span("dedup.resolve", trace=tid, regime="oneshot", docs=n):
            if self._rerank_applied:
                # the tier settled every cell by TRUE (sketch/exact)
                # Jaccard and already paid its precision eviction —
                # re-litigating with the estimator-era exact-verify
                # stage would refute deliberate keeps (settled recall
                # pairs with true J just under threshold) and re-admit
                # nothing: resolve the rewritten matrix verbatim
                rb_host = np.asarray(rep_bands)
                ok = rb_host != np.arange(
                    rb_host.shape[0], dtype=rb_host.dtype
                )[:, None]
            else:
                ok = self._exact_verified_ok(
                    raw, sigs, keys, valid, rep_bands
                )
            rep = resolve_rep_bands_from_ok(
                rep_bands, ok, valid, jump_rounds=_jump_rounds(n_bucket)
            )
            stages.count_dispatch("dedup")
            out = np.asarray(rep)[:n]
        self._count_result("oneshot", n, out)
        self._emit_decisions("oneshot", out, keys, n)
        return out

    def keep(self, texts: Sequence[str | bytes]) -> np.ndarray:
        reps = self.dedup_reps(texts)
        return reps == np.arange(len(reps))

    def signatures_and_keys(
        self,
        texts: Sequence[str | bytes],
        *,
        wide: bool = False,
        sync_sigs: bool = True,
    ) -> tuple[np.ndarray | None, np.ndarray]:
        """Host ``(sigs[:N], keys[:N])`` with the keys computed ON DEVICE
        from the device-resident accumulator — one fused epilogue dispatch
        (``ops.lsh.fused_keys_epilogue``).

        ``wide=False`` returns the coarse+fine candidate keys
        (``candidate_keys`` semantics — ``uint32[N, nb+cand_subbands]``);
        ``wide=True`` the two-lane wide keys (``band_keys_wide`` —
        ``uint32[N, nb, 2]``, pack on host).  Replaces the streaming
        backends' old shape — sync host signatures, then feed them BACK
        through ``band_keys*`` (a D2H → re-H2D bounce plus extra
        dispatches per batch on a tunneled transport).

        ``sync_sigs=False`` returns ``(None, keys)``: callers that only
        consume keys (the bloom/persist stream indexes — neither stores
        signatures) skip the ``uint32[bucket, num_perm]`` D2H entirely,
        which on a tunneled link is ~8× the key volume for nothing.
        """
        from advanced_scrapper_tpu.obs import stages, trace

        fused_keys_epilogue = _lsh_epilogue("fused_keys_epilogue")
        n = len(texts)
        if n == 0:
            nb = self.params.num_bands
            shape = (0, nb, 2) if wide else (0, nb + self.cfg.cand_subbands)
            sigs0 = np.zeros((0, self.params.num_perm), np.uint32)
            return (sigs0 if sync_sigs else None), np.zeros(shape, np.uint32)
        tid = trace.new_trace_id()
        raw = [to_bytes(t) for t in texts]
        running, _n_bucket, use_oph = self._accumulate_device(
            raw, trace_id=tid
        )
        sig_dev, keys_dev = fused_keys_epilogue(
            running,
            np.asarray(self.params.band_salt),
            self._fine_salt(),
            densify_oph=use_oph,
            wide=wide,
        )
        stages.count_dispatch("dedup")
        with stages.timed("kernel"), trace.span(
            "dedup.readback", trace=tid, docs=n
        ):  # readback sync: the device drains here
            sigs = np.asarray(sig_dev)[:n] if sync_sigs else None
            return sigs, np.asarray(keys_dev)[:n]

    def open_stream_index(self, index_dir: str):
        """Open the durable stream index this engine's config names: a
        local :class:`~advanced_scrapper_tpu.index.store.PersistentIndex`
        under ``index_dir``, or — when ``cfg.index_fleet`` is set — a
        :class:`~advanced_scrapper_tpu.index.fleet.ShardedIndexClient`
        over the remote shard fleet (``index_dir`` then holds only the
        degraded-mode spill journals).  Either return value is a valid
        ``index`` argument to :meth:`dedup_against_index` — the fleet is
        a config string, not a call-site change."""
        if self.cfg.index_fleet:
            from advanced_scrapper_tpu.index.fleet import open_fleet_index

            return open_fleet_index(self.cfg, index_dir, space="bands")
        from advanced_scrapper_tpu.index import PersistentIndex

        return PersistentIndex(
            index_dir,
            cut_postings=self.cfg.index_cut_postings,
            compact_segments=self.cfg.index_compact_segments,
        )

    def dedup_against_index(
        self, texts: Sequence[str | bytes], index, doc_ids=None, *, mesh=None
    ) -> np.ndarray:
        """``int64[N]`` attribution of a corpus against a persistent index
        (``index.store.PersistentIndex`` — or its fleet drop-in,
        ``index.fleet.ShardedIndexClient``; ``open_stream_index`` picks by
        config): device signatures → wide uint64 band keys →
        ``check_and_add_batch``.  A row whose result is ≥ 0 is
        a near-dup of that (possibly restarts-old) doc id; fresh rows post
        their keys under ``doc_ids`` (allocated from the index when not
        given) and return -1.  Sub-shingle rows are never probed or posted
        (always -1) — same eligibility rule as every stream index.

        This is the engine-level streaming entry the persistent index was
        built for: the batch backend (`extractors/tpu_batch.py`) wraps it
        with record bookkeeping, but a raw corpus stream can consume it
        directly.

        ``mesh=``: compute the band keys on the mesh-sharded packed plane
        (per-shard fused donated tiles, ``pmin``-combined keys epilogue)
        instead of the single-device accumulator — byte-identical keys,
        so attributions never depend on the device topology.  The
        cross-shard band-key merge then rides the index plane on the
        host: a ``ShardedIndexClient`` fans each key to its ring shard
        (probe row-min + replicated insert), which is deliberately
        decoupled from the device-mesh shard count.  (With the legacy
        transport forced — ``ASTPU_DEDUP_PACKED_H2D=0`` — ``mesh`` is
        ignored: the oracle transport has no sharded keys plane.)
        """
        from advanced_scrapper_tpu.utils.bloom import pack_keys64

        n = len(texts)
        out = np.full((n,), -1, np.int64)
        if n == 0:
            return out
        raw = [to_bytes(t) for t in texts]
        # fused epilogue: the wide keys come off the device-resident
        # accumulator in one dispatch — signatures never bounce D2H→H2D,
        # and are never synced at all (the index stores keys only)
        if mesh is not None and self.cfg.packed_h2d:
            keys_wide = self._keys_wide_sharded(raw, mesh)
        else:
            _sigs, keys_wide = self.signatures_and_keys(
                raw, wide=True, sync_sigs=False
            )
        keys64 = pack_keys64(keys_wide)
        if (
            self.ladder is not None
            and self.ladder.active("fewer_bands")
            and keys64.ndim == 2
            and keys64.shape[1] > 1
        ):
            # brownout step 3: probe/post only the first half of the LSH
            # bands — a declared recall brownout (fewer probe rows, fewer
            # postings) that reverses when the step exits; rows posted
            # while degraded keep their reduced band set, which is the
            # counted cost of staying up
            keys64 = keys64[:, : max(1, keys64.shape[1] // 2)]
            self.ladder.count_effect("fewer_bands", n)
        eligible = np.fromiter(
            (len(r) >= self.params.shingle_k for r in raw), bool, n
        )
        if not eligible.any():
            return out
        if doc_ids is None:
            doc_ids = index.allocate_doc_ids(n)
        doc_ids = np.asarray(doc_ids, dtype=np.uint64)
        out[eligible] = index.check_and_add_batch(
            keys64[eligible], doc_ids[eligible]
        )
        self._emit_index_decisions(out, keys64, eligible, index)
        return out


class ExactDedup:
    """First-seen exact dedup with a byte-identical guarantee.

    Default path: ONE native pass (``cpu.hostbatch.exact_keep_first_native``)
    — the corpus flattens into a single byte blob + offset table and a
    C-side open-addressing hash table decides first-seen membership,
    settling every hash-equal probe with a full ``memcmp`` (a collision can
    lengthen a probe chain but never drop a distinct row).  This is the
    pandas ``drop_duplicates(keep='first')`` replacement that actually
    out-runs pandas: no per-row Python objects, no device round trip, one
    preallocated uint64 offset array and one uint8 keep mask.

    Fallback (no compiler, mixed str/bytes input, or a caller-supplied
    hasher): the device proposes equality groups via 128-bit hashes and the
    host walks each group in original order comparing *actual* full strings
    — including past any hash-side truncation — so the kept index set
    equals the pandas path exactly on every route.
    """

    def __init__(self, hasher: ExactHasher | None = None, max_len: int = 4096):
        # A caller-supplied hasher pins the grouping path (tests inject
        # degenerate hashers; the native pass would ignore them).
        self._custom_hasher = hasher is not None
        self.hasher = hasher or ExactHasher()
        # Historical name: rows are hashed blockwise at this width, so it no
        # longer caps item length — any size hashes exactly (the linear hash
        # splits across blocks; see ``ExactHasher.hash_docs``).
        self.max_len = max_len
        #: which tier served the most recent :meth:`keep_indices` call:
        #: "zero-copy" | "blob" | "grouping" — BENCH_r05's silent 0.22×
        #: regression was the grouping fallback running where the native
        #: tiers should have (build failure swallowed); the bench now
        #: reports this so path selection is a measured fact
        self.last_path: str = ""

    def keep_indices(self, items: Sequence[str]) -> list[int]:
        keep = self._keep_indices(items)
        if items:
            # decision provenance: the exact (memcmp) tier settled every
            # verdict here — kept rows are first-seen uniques, the rest
            # byte-identical dups of an earlier row
            from advanced_scrapper_tpu.obs.decisions import get_recorder

            rec = get_recorder()
            rec.count("exact", "unique", len(keep))
            rec.count("exact", "dup", len(items) - len(keep))
        return keep

    def _keep_indices(self, items: Sequence[str]) -> list[int]:
        if not items:
            return []
        if not self._custom_hasher:
            from advanced_scrapper_tpu.cpu.exactdedup import keep_first_list
            from advanced_scrapper_tpu.cpu.hostbatch import (
                exact_keep_first_native,
            )

            # zero-copy tier first (reads str/bytes buffers in place), then
            # the blob tier (one join + offsets); both confirm every
            # hash-equal probe with a full memcmp, so each is byte-identical
            # to the pandas path on the inputs it accepts
            keep = keep_first_list(items)
            self.last_path = "zero-copy"
            if keep is None:
                keep = exact_keep_first_native(items)
                self.last_path = "blob"
            if keep is not None:
                return np.flatnonzero(keep).tolist()
        self.last_path = "grouping"
        n = len(items)
        raw = [to_bytes(s) for s in items]
        block = bucket_len(max(1, min(max(len(r) for r in raw), self.max_len)))
        h = self.hasher.hash_docs(raw, block_len=block)  # uint32[N, 4]
        # Group rows by their 128-bit hash with one C-speed lexsort instead
        # of a per-row Python dict walk: rows whose hash is unique are kept
        # outright, and only multi-member groups (true duplicates or 2⁻¹²⁸
        # collisions) ever reach the Python string-confirm below.
        hi = (h[:, 0].astype(np.uint64) << 32) | h[:, 1]
        lo = (h[:, 2].astype(np.uint64) << 32) | h[:, 3]
        order = np.lexsort((lo, hi))  # stable ⇒ ties stay in original order
        shi, slo = hi[order], lo[order]
        new_group = np.empty(n, bool)
        new_group[0] = True
        new_group[1:] = (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])
        gid = np.empty(n, np.int64)
        gid[order] = np.cumsum(new_group) - 1
        # per-group leader = smallest original index (stability of lexsort)
        leader_of = order[np.flatnonzero(new_group)]
        counts = np.bincount(gid)
        keep = counts[gid] == 1  # singleton hash ⇒ provably first-seen unique
        multi_rows = np.flatnonzero(~keep)  # ascending ⇒ original order
        if len(multi_rows):
            # The overwhelming case is a true-duplicate group: every member
            # equals its leader.  One C-level object compare settles all of
            # them; only groups holding a member that DIFFERS from the
            # leader (a 2⁻¹²⁸ hash collision) take the per-group walk.
            obj = np.array(items, dtype=object)
            leaders = leader_of[gid[multi_rows]]
            eq_leader = obj[multi_rows] == obj[leaders]
            keep[leader_of] = True  # singleton leaders were already True
            rare = np.unique(gid[multi_rows[~eq_leader]])
            for g in rare.tolist():
                members = multi_rows[gid[multi_rows] == g]
                kept_distinct: list[int] = []
                for i in members.tolist():
                    if not any(items[j] == items[i] for j in kept_distinct):
                        kept_distinct.append(i)
                        keep[i] = True
                    else:
                        keep[i] = False
        return np.flatnonzero(keep).tolist()

    def keep_mask(self, items: Sequence[str]) -> np.ndarray:
        mask = np.zeros(len(items), dtype=bool)
        mask[self.keep_indices(items)] = True
        return mask
