"""Dedup engines — the TPU rerouting of the reference's dedup steps.

- :class:`NearDupEngine` — MinHash(k=5, 128-perm) + 16-band LSH near-dup
  clustering (the north-star workload; no analogue in the reference, which
  only ever does exact dedup).
- :class:`ExactDedup` — byte-identical replacement for pandas
  ``drop_duplicates(subset=['url'], keep='first')``
  (``yahoo_links_selenium.py:79,174``): 128-bit device hashing proposes
  groups, the host confirms true string equality inside each group, so the
  surviving row set is *provably* identical to the pandas path.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import MinHashParams, make_params
from advanced_scrapper_tpu.core.tokenizer import (
    bucket_len,
    encode_batch,
    encode_blocks,
    to_bytes,
)
from advanced_scrapper_tpu.ops.exact import ExactHasher
from advanced_scrapper_tpu.ops.lsh import band_keys, duplicate_reps, keep_mask, resolve_reps
from advanced_scrapper_tpu.ops.minhash import (
    combine_block_signatures,
    resolve_signature_fn,
)


def _jump_rounds(n: int) -> int:
    r = 1
    while (1 << r) < n:
        r += 1
    return r


class NearDupEngine:
    """Batch near-duplicate detector.

    Long texts are split into overlapping blocks (`core.tokenizer.encode_blocks`)
    so device shapes stay fixed; block signatures are exactly min-combined per
    article. Block batches are padded to a fixed size to avoid recompilation.
    """

    def __init__(self, cfg: DedupConfig | None = None, params: MinHashParams | None = None):
        self.cfg = cfg or DedupConfig()
        self.params = params or make_params(
            num_perm=self.cfg.num_perm,
            num_bands=self.cfg.num_bands,
            shingle_k=self.cfg.shingle_k,
            seed=self.cfg.seed,
        )

    def signatures(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """uint32[N, num_perm] MinHash signatures (blockwise, batched).

        With ``cfg.backend == "oph"`` block signatures are the *raw* OPH
        form (empty bins ``U32_MAX``) so the per-article segment-min combine
        stays exact; densification runs once after the combine (see
        ``ops/oph.py`` for why that order is load-bearing).
        """
        cfg, params = self.cfg, self.params
        if len(texts) == 0:
            return np.zeros((0, params.num_perm), np.uint32)
        block_fn = resolve_signature_fn(cfg.backend)  # validates the name
        use_oph = cfg.backend == "oph"
        if use_oph:
            from advanced_scrapper_tpu.ops.oph import densify, oph_raw_signatures

            block_fn = oph_raw_signatures  # densify AFTER the block combine

        tok, lens, owners = encode_blocks(
            texts, cfg.block_len, overlap=params.shingle_k - 1
        )
        n_blocks = tok.shape[0]
        bs = cfg.batch_size
        sig_parts = []
        for start in range(0, n_blocks, bs):
            t = tok[start : start + bs]
            l = lens[start : start + bs]
            if t.shape[0] < bs:
                pad = bs - t.shape[0]
                t = np.concatenate([t, np.zeros((pad, t.shape[1]), np.uint8)])
                l = np.concatenate([l, np.zeros((pad,), np.int32)])
            sig_parts.append(np.asarray(block_fn(t, l, params)))
        sigs = np.concatenate(sig_parts)[:n_blocks]
        # Bucket the article count so combine compiles O(log N) variants, not
        # one per corpus size (same trick as the block-length axis).
        n_bucket = bucket_len(len(texts), min_bucket=64)
        combined = combine_block_signatures(sigs, owners, num_articles=n_bucket)
        if use_oph:
            combined = densify(combined)
        return np.asarray(combined)[: len(texts)]

    def dedup_reps(self, texts: Sequence[str | bytes]) -> np.ndarray:
        """int32[N] first-seen-wins representative per text (union-find roots)."""
        n = len(texts)
        if n == 0:
            return np.zeros((0,), np.int32)
        sigs = self.signatures(texts)
        lens = np.array([len(to_bytes(t)) for t in texts])
        valid = lens >= self.params.shingle_k
        # Pad the corpus axis to a bucket: padded rows are invalid, so they
        # self-assign and never affect real rows; compiled shapes stay O(log N).
        n_bucket = bucket_len(n, min_bucket=64)
        if n_bucket != n:
            sigs = np.concatenate(
                [sigs, np.full((n_bucket - n, sigs.shape[1]), 0xFFFFFFFF, np.uint32)]
            )
            valid = np.concatenate([valid, np.zeros(n_bucket - n, bool)])
        keys = band_keys(sigs, self.params.band_salt)
        rep = duplicate_reps(keys, valid)
        rep = resolve_reps(
            rep, sigs, valid, self.cfg.sim_threshold,
            jump_rounds=_jump_rounds(n_bucket),
        )
        return np.asarray(rep)[:n]

    def keep(self, texts: Sequence[str | bytes]) -> np.ndarray:
        reps = self.dedup_reps(texts)
        return reps == np.arange(len(reps))


class ExactDedup:
    """First-seen exact dedup with a byte-identical guarantee.

    The device proposes equality groups via 128-bit hashes; the host walks
    each group in original order comparing *actual* strings, so a 2⁻¹²⁸
    collision can propose but never cause a wrong drop.  Result: the kept
    index set equals pandas ``drop_duplicates(keep='first')`` exactly.
    """

    def __init__(self, hasher: ExactHasher | None = None, max_len: int = 4096):
        self.hasher = hasher or ExactHasher()
        self.max_len = max_len

    def keep_indices(self, items: Sequence[str]) -> list[int]:
        if not items:
            return []
        longest = max(len(s.encode("utf-8", "replace")) for s in items)
        if longest > self.max_len:
            raise ValueError(
                f"item of {longest} bytes exceeds max_len {self.max_len}; "
                "raise max_len so hashing covers every byte (truncated hashing "
                "would break the byte-identical guarantee)"
            )
        L = bucket_len(max(longest, 1))
        tok, lens = encode_batch(items, block_len=L)
        h = np.asarray(self.hasher(tok, lens))  # uint32[N, 4]
        first_by_hash: dict[bytes, list[int]] = {}
        kept: list[int] = []
        for i in range(len(items)):
            key = h[i].tobytes()
            group = first_by_hash.get(key)
            if group is None:
                first_by_hash[key] = [i]
                kept.append(i)
            else:
                # hash collision group: confirm a true string match
                if any(items[j] == items[i] for j in group):
                    continue
                group.append(i)
                kept.append(i)
        return kept

    def keep_mask(self, items: Sequence[str]) -> np.ndarray:
        mask = np.zeros(len(items), dtype=bool)
        mask[self.keep_indices(items)] = True
        return mask
