"""Cross-source pod-scale dedup (BASELINE.json config 5).

Merges articles from heterogeneous sources — scraper success CSVs
(``success_articles_*.csv``) and SQLite article stores
(``crypto_news.db``-style) — into one corpus and runs exact + near-dup
detection across ALL of them, so e.g. a Yahoo article and its syndicated
copy in the BTC store collapse to one representative.  Per-source stats are
reported; a merged "keep" manifest CSV is written.

All corpora stream through :class:`extractors.tpu_batch.TpuBatchBackend`
(fixed-size device batches + persistent host bucket index), so memory stays
bounded regardless of corpus size; static in-memory corpora can instead use
``parallel.sharded.make_sharded_dedup`` directly for an all-device join.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterator

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.extractors.tpu_batch import TpuBatchBackend
from advanced_scrapper_tpu.storage.csvio import AppendCsv
from advanced_scrapper_tpu.storage.stores import ArticleStore


@dataclass
class SourceDoc:
    source: str
    url: str
    text: str


def load_source(path: str) -> Iterator[SourceDoc]:
    """A source is a success CSV (url/article columns) or a sqlite DB.

    Yields lazily so the host never materialises a whole corpus.
    """
    name = os.path.basename(path)
    if path.endswith((".db", ".sqlite", ".sqlite3")):
        store = ArticleStore(path)
        for url, text in store.all_texts():
            yield SourceDoc(name, url, text)
        return
    import csv as _csv

    with open(path, newline="", encoding="utf-8") as f:
        for row in _csv.DictReader(f):
            text = row.get("article") or row.get("article_text") or ""
            yield SourceDoc(name, str(row.get("url", "")), text)


def _write_rec(rec: dict, stats: dict, out: AppendCsv) -> None:
    src = rec.get("_source", "")
    s = stats["by_source"].setdefault(src, {"total": 0, "kept": 0, "dups": 0})
    s["total"] += 1
    if rec.get("dup_of"):
        status, ref = "exact_dup", rec["dup_of"]
        stats["exact_dups"] += 1
        s["dups"] += 1
    elif rec.get("near_dup_of"):
        status, ref = "near_dup", rec["near_dup_of"]
        stats["near_dups"] += 1
        s["dups"] += 1
    else:
        status, ref = "keep", ""
        stats["kept"] += 1
        s["kept"] += 1
    out.write_row(
        {"url": rec.get("url", ""), "source": src, "status": status, "dup_of": ref}
    )


def cross_source_dedup(
    sources: list[str],
    output_csv: str,
    *,
    cfg: DedupConfig | None = None,
) -> dict:
    """Dedup across sources → manifest CSV + per-source stats dict.

    Documents stream source-by-source into the batch backend and manifest
    rows are written as each device batch resolves, so host memory is
    O(batch), not O(corpus).  The manifest describes exactly this run: a
    stale file at ``output_csv`` is truncated, not appended to.
    """
    cfg = cfg or DedupConfig()
    if os.path.exists(output_csv):
        os.remove(output_csv)

    backend = TpuBatchBackend(cfg)
    stats: dict = {"total": 0, "kept": 0, "exact_dups": 0, "near_dups": 0,
                   "by_source": {}}
    with AppendCsv(output_csv, ["url", "source", "status", "dup_of"]) as out:
        for src_path in sources:
            for d in load_source(src_path):
                stats["total"] += 1
                for rec in backend.submit(
                    {"url": d.url, "article": d.text, "_source": d.source}
                ):
                    _write_rec(rec, stats, out)
        for rec in backend.flush():
            _write_rec(rec, stats, out)
    return stats
