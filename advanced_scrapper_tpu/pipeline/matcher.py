"""L5: entity→article matching, rerouted through the TPU q-gram screen.

Re-implements ``match_keywords.py`` end to end:

- **entity loading** (ref ``:40-120``): every ``info/*.json`` (utf-8 → gbk →
  latin1 fallback chain), company filter ``(len >= 2 and 'United States' in
  country) or len <= 1``, and ``"Name (Start: …) (End: …)"`` suffix parsing
  into per-name date windows;
- **match rules** (ref ``:159-180``), byte-identical decisions:
  - ALL-CAPS names of length > 1 → ``\\b re.escape(name) \\b`` positions in
    article text and title;
  - names that are not pure-lowercase-alphabetic → fuzzy
    ``partial_ratio(text, name) > 95`` (native C++ kernel; exact score
    parity with installed rapidfuzz 3.x is CI-fuzzed in
    ``tests/test_rapidfuzz_parity.py``), positions via un-escaped
    ``re.finditer`` like the ref;
  - everything else is skipped entirely;
  - a name only counts when the article date is inside its window
    (``is_within_period``, naive datetimes promoted to UTC, ref ``:17-37``);
- **outputs** (ref ``:128-146,195-217``): per-ticker
  ``{source}_ticker_matched_articles/{ticker}_match.csv`` rows with
  JSON-encoded match-position dicts, then a final per-file sort by
  ``time_unix``.

The TPU reroute: instead of scanning every (article × name) pair on the
host (the reference's quadratic hot loop), a device q-gram screen
(``ops/match.py``) prunes pairs first; only survivors are verified with the
exact host rules above, so outputs cannot differ — golden-tested against a
pure reference implementation.  A second device stage (``use_refine``: the
Myers alignment bound, ``ops/editdist.py``) can prune screen survivors
whose text-side fuzzy score is provably ≤ threshold before the host scorer
runs — output-identical (golden-tested), default **"auto"** (r5 verdict):
whether the bound pays depends on the prune yield and the host/device
cost ratio of the actual backend+corpus — a decoy-heavy corpus runs
2.2× FASTER with it, the bench corpus 1.9× SLOWER, and surviving-pair
count points the wrong way in both cases (the r4 gate's mistake; it
cost the tracked matcher metric 38%).  So "auto" is a measured RACE:
``run_matcher`` probes both modes on real chunks and exploits the
winner (:class:`RefineController`); direct ``match_chunk`` calls
without a measurement run screen-only.  The r3 always-on loss
(63 s vs 2.6 s) was the tunnel's per-slice dispatch latency — the race
measures that too, so tunneled transports converge to screen-only
without a special case (``--no-refine`` still forces it).

The device path rides the SAME single-dispatch plane as the dedup
engine (PR 9 → PR 10): chunks split into byte-budget width-bucketed
screen tiles, each crossing H2D as ONE packed buffer
(``ops/pack.py``, 5 int32 trailer planes) into ONE fused jitted
screen(+Myers-bound) dispatch (``ops.match.make_screen_step``), all
pipelined encode/pack → h2d → dispatch through the dispatch executor
(``pipeline/dispatch.py``) with a bounded in-flight window — so a tile
is exactly 1 put + 1 dispatch, gated numerically by the always-on
device counters (tier-1 ``tests/test_match_dispatch.py``, ``bench
--regime matcher``).  The refine race picks fused-vs-screen-only
MODES of that one step, not separate kernels.  ``ASTPU_MATCH_PACKED=0``
keeps the legacy per-batch screen loop (``_legacy_screen``) runnable —
byte-identical output, certified across screen-only / refine /
overlong-fallback / pooled-verify modes.

Documented divergences from the reference (both are reference *crashes*):
- a fuzzy-matched name that is itself an invalid regex falls back to
  escaped-literal position search (the ref raises ``re.error`` mid-chunk);
- matched articles whose ``date_time`` cannot be parsed are skipped with a
  warning (the ref raises inside ``append_to_csv``).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

import numpy as np
import pandas as pd
from dateutil import parser as dateparser
from dateutil.tz import tzutc

from advanced_scrapper_tpu.config import MatchConfig
from advanced_scrapper_tpu.cpu import native

# ops.match (and through it jax) is imported lazily inside the screen path:
# verify-pool workers must stay jax-free (they only run the host rules), and
# CLI paths that never screen shouldn't pay device-runtime import time.

ATTRIBUTES = (
    "id_label",
    "ticker",
    "aliases",
    "products",
    "subsidiaries",
    "owned_entities",
    "ceos",
    "board_members",
)  # ref :76-85

OUTPUT_FIELDS = [
    "time_unix",
    "date_time",
    "text_matches",
    "title_matches",
    "title",
    "url",
    "source",
    "source_url",
    "article_text",
]  # ref :134-144


# -- reference parsing helpers ---------------------------------------------


def is_within_period(article_date, start_date, end_date) -> bool:
    """Date-window gate (ref :17-37); naive datetimes are promoted to UTC."""
    if article_date is None:
        return False
    if article_date.tzinfo is None:
        article_date = article_date.replace(tzinfo=tzutc())
    if start_date is not None and start_date.tzinfo is None:
        start_date = start_date.replace(tzinfo=tzutc())
    if end_date is not None and end_date.tzinfo is None:
        end_date = end_date.replace(tzinfo=tzutc())
    if start_date and end_date:
        return start_date <= article_date <= end_date
    if start_date:
        return start_date <= article_date
    if end_date:
        return article_date <= end_date
    return True


def extract_time_periods(names) -> dict[str, tuple]:
    """``"Name (Start: …) (End: …)"`` → {name: (start, end)} (ref :40-65)."""
    periods: dict[str, tuple] = {}
    if isinstance(names, str):
        names = [names]
    for info in names:
        parts = info.split(" (")
        name = parts[0].strip()
        start = end = None
        for part in parts[1:]:
            if "Start:" in part:
                raw = part.replace("Start:", "").replace("T00:00:00Z)", "").strip()
                try:
                    start = dateparser.parse(raw)
                except (ValueError, dateparser.ParserError):
                    start = None
            elif "End:" in part:
                raw = part.replace("End:", "").replace("T00:00:00Z)", "").strip()
                try:
                    end = dateparser.parse(raw)
                except (ValueError, dateparser.ParserError):
                    end = None
        periods[name] = (start, end)
    return periods


def process_json_data(json_data: list) -> dict:
    """US-company filter + per-attribute period maps (ref :68-87)."""
    result = {}
    for company in json_data:
        if (len(json_data) >= 2 and "United States" in company.get("country", [])) or len(
            json_data
        ) <= 1:
            ticker = company["ticker"]
            result[ticker] = {
                attr: extract_time_periods(company.get(attr, [])) for attr in ATTRIBUTES
            }
    return result


def read_info_dir(folder: str) -> dict:
    """Load every info JSON with the encoding fallback chain (ref :90-120)."""
    out: dict = {}
    for filename in sorted(os.listdir(folder)):
        if not filename.endswith(".json"):
            continue
        path = os.path.join(folder, filename)
        data = None
        for enc in ("utf-8", "gbk", "latin1"):
            try:
                with open(path, "r", encoding=enc) as f:
                    data = json.load(f)
                break
            except (UnicodeDecodeError, json.JSONDecodeError):
                continue
        if data is None:
            print(f"could not read {filename}")
            continue
        out.update(process_json_data(data))
    return out


# -- flattened entity index (screen-ready) ----------------------------------


@dataclass(frozen=True)
class NameEntry:
    ticker: str
    attribute: str
    name: str
    start: object
    end: object
    is_exact_upper: bool  # ALL-CAPS word-boundary path
    # (fuzzy otherwise; unreachable names are never stored)


class EntityIndex:
    """Flat, screen-ready view of the processed entity data."""

    def __init__(self, processed: dict):
        self.processed = processed
        self.entries: list[NameEntry] = []
        for ticker, attrs in processed.items():
            for attribute, names in attrs.items():
                for name, (start, end) in names.items():
                    if not name:
                        # empty names (reachable via extract_time_periods on
                        # strings starting " (") score partial_ratio 0.0 in
                        # rapidfuzz 3.x — they can never match; storing them
                        # would only waste screen lanes
                        continue
                    if name.isupper():
                        if len(name) > 1:
                            self.entries.append(
                                NameEntry(ticker, attribute, name, start, end, True)
                            )
                        # single-char upper names never match (ref :166)
                    elif not (name.islower() and name.replace(" ", "").isalpha()):
                        self.entries.append(
                            NameEntry(ticker, attribute, name, start, end, False)
                        )
                    # pure-lowercase-alpha names are skipped (ref :174)
        self._tables: dict | None = None
        self._refine_tables: tuple | None = None
        self._verify_arena = None
        self._upper_matcher: tuple | None = None
        #: compiled packed screen steps per mode (False = screen-only,
        #: True = fused screen+bound) — built lazily by ``_screen_steps``
        self._packed_steps: dict = {}
        #: optional per-tile observer ``(dict) -> None`` on the packed
        #: dispatch loop (tile index, rows, width, h2d_bytes, put/dispatch
        #: ms) — ``tools/profile_hostpath.py --device`` renders it
        self.dispatch_probe = None

    @classmethod
    def from_info_dir(cls, folder: str) -> "EntityIndex":
        return cls(read_info_dir(folder))

    def screen_tables(self) -> dict:
        if self._tables is None:
            from advanced_scrapper_tpu.obs import stages
            from advanced_scrapper_tpu.ops.match import prepare_names

            with stages.timed("matcher_build"):
                names = [e.name.encode("utf-8", "replace") for e in self.entries]
                fuzzy = np.array(
                    [not e.is_exact_upper for e in self.entries], bool
                )
                self._tables = prepare_names(names, fuzzy=fuzzy)
        return self._tables

    def upper_matcher(self):
        """``(MultiPattern | None, {name: pattern_id})`` over the unique
        ASCII ALL-CAPS names — the single-pass multi-pattern core that
        replaces per-name ``\\b re.escape(name) \\b`` scans.  Built lazily
        ONCE per EntityIndex (so streaming chunks never rebuild it) and
        never pickled: verify-pool workers reconstruct the index from
        ``processed`` at init and build their own on first use.  None when
        no native backend (or no eligible names) — callers keep the
        per-name regex path, which stays the behavioural oracle."""
        if self._upper_matcher is None:
            from advanced_scrapper_tpu.cpu.native import MultiPattern
            from advanced_scrapper_tpu.obs import stages

            with stages.timed("matcher_build"):
                names = sorted({
                    e.name for e in self.entries
                    if e.is_exact_upper and e.name.isascii()
                })
                mp = None
                if names:
                    cand = MultiPattern([n.encode("ascii") for n in names])
                    mp = cand if cand.available else None
                self._upper_matcher = (mp, {n: i for i, n in enumerate(names)})
        return self._upper_matcher

    def verify_arena(self):
        """Packed-needle arena over all entry names (rows = entry index),
        built lazily once per EntityIndex — the native verify scores
        screened rows against it without per-article re-encoding.  Pool
        workers rebuild the index from ``processed`` at init and each build
        their own arena on first use; the parent's is never shipped."""
        if self._verify_arena is None:
            self._verify_arena = native.CutoffArena(
                [e.name for e in self.entries]
            )
        return self._verify_arena


# -- matching ----------------------------------------------------------------


def _find_positions(pattern: str, text: str) -> list[int]:
    return [m.start() for m in re.finditer(pattern, text)]


# ASCII \w table (letters, digits, underscore): on ASCII text this is
# exactly Python re's Unicode \w membership, which is what the boundary
# replay below must reproduce.
_ASCII_WORD = bytes(
    1 if (chr(c).isalnum() or c == ord("_")) else 0 for c in range(128)
) + bytes(128)


def _upper_positions(index: "EntityIndex", text: str) -> dict[str, list[int]] | None:
    """Per-name start positions of every ALL-CAPS name in ``text`` via ONE
    automaton pass — output-identical to running
    ``re.finditer(r"\\b" + re.escape(name) + r"\\b", text)`` per name.

    None routes the caller to the per-name regex path (no native automaton,
    or non-ASCII text, where byte offsets would diverge from char offsets).
    The \\b replay: a boundary holds where exactly one side is a word char,
    so each raw automaton hit checks its edge bytes against the name's edge
    bytes; surviving hits then replay finditer's non-overlap rule per name
    (a match consumes its span; a boundary-rejected occurrence consumes
    nothing).  Names absent from the dict simply have no matches.
    """
    mp, mid_of = index.upper_matcher()
    if mp is None or not text.isascii():
        return None
    data = text.encode("ascii")
    ids, starts = mp.scan(data)
    out: dict[str, list[int]] = {}
    if not len(ids):
        return out
    n = len(data)
    last_end: dict[int, int] = {}
    names = mp.patterns
    for i, s in zip(ids.tolist(), starts.tolist()):
        nb = names[i]
        e = s + len(nb)
        # \b before: boundary between text[s-1] and name[0]
        if _ASCII_WORD[nb[0]]:
            if s > 0 and _ASCII_WORD[data[s - 1]]:
                continue
        elif s == 0 or not _ASCII_WORD[data[s - 1]]:
            continue
        # \b after: boundary between name[-1] and text[e]
        if _ASCII_WORD[nb[-1]]:
            if e < n and _ASCII_WORD[data[e]]:
                continue
        elif e >= n or not _ASCII_WORD[data[e]]:
            continue
        if s < last_end.get(i, 0):
            continue  # finditer resumes at the previous match's end
        last_end[i] = e
        out.setdefault(nb.decode("ascii"), []).append(s)
    return out


def _find_positions_literal_fallback(name: str, text: str) -> list[int]:
    try:
        return _find_positions(name, text)
    except re.error:
        return _find_positions(re.escape(name), text)


def match_article(
    text: str,
    title: str,
    article_date,
    index: EntityIndex,
    candidate_mask: np.ndarray | None = None,
    threshold: float = 95.0,
    text_pruned: set | None = None,
) -> dict:
    """Exact match rules for one article → {ticker: {'text': …, 'title': …}}.

    ``candidate_mask[j]`` (from the TPU screen) prunes name j; None means
    scan everything (the pure reference path used for goldens).
    ``text_pruned`` holds name indices whose *text-side* fuzzy score is
    device-proven ≤ threshold (``ops/editdist.py`` Myers bound) — the
    expensive long-text ``partial_ratio`` call is skipped for those; the
    title side still runs (the bound applies per part).
    """
    per_ticker: dict[str, dict] = {}

    def slot(ticker: str) -> dict:
        return per_ticker.setdefault(ticker, {"text": {}, "title": {}})

    # Pass 1: filter (screen mask + date window) and split by rule kind.
    # Fuzzy scores batch into ONE native call per (article, side) —
    # per-name calls re-encode the whole article and pay a ctypes round
    # trip each (measured ~65 screened names/article); decisions and the
    # j-ascending insert order below are identical to the per-name loop.
    pending: list[tuple[int, object]] = []
    text_rows: list[int] = []   # entry indices j to score against the text
    title_rows: list[int] = []  # entry indices j to score against the title
    entries = index.entries
    if candidate_mask is None:
        survivors = range(len(entries))
    else:
        # iterate screen survivors only (C-level nonzero), not every entry
        survivors = np.flatnonzero(candidate_mask).tolist()
    any_upper = False
    for j in survivors:
        e = entries[j]
        if not is_within_period(article_date, e.start, e.end):
            continue
        pending.append((j, e))
        if not e.is_exact_upper:
            # text side skipped when the device bound proved it ≤ threshold
            if text_pruned is None or j not in text_pruned:
                text_rows.append(j)
            title_rows.append(j)
        else:
            any_upper = True

    arena = index.verify_arena()
    text_score = dict(zip(text_rows, arena.scores(text, text_rows, threshold)))
    title_score = dict(zip(title_rows, arena.scores(title, title_rows, threshold)))

    # ALL-CAPS positions: one automaton pass per article part replaces the
    # per-name \b regex scans (identical output; _upper_positions).  None
    # (no native core / non-ASCII part) keeps the regex path per part.
    auto_names: dict | None = None
    text_hits = title_hits = None
    if any_upper:
        auto_names = index.upper_matcher()[1]
        text_hits = _upper_positions(index, text)
        title_hits = _upper_positions(index, title)

    # Pass 2: apply the decisions in the original j order.
    for j, e in pending:
        if e.is_exact_upper:
            # positions are the decision (ref :165-173)
            in_auto = auto_names is not None and e.name in auto_names
            pattern = None
            if in_auto and text_hits is not None:
                text_pos = text_hits.get(e.name, [])
            else:
                pattern = r"\b" + re.escape(e.name) + r"\b"
                text_pos = _find_positions(pattern, text)
            if in_auto and title_hits is not None:
                title_pos = title_hits.get(e.name, [])
            else:
                if pattern is None:
                    pattern = r"\b" + re.escape(e.name) + r"\b"
                title_pos = _find_positions(pattern, title)
            if text_pos:
                slot(e.ticker)["text"][e.name] = text_pos
            if title_pos:
                slot(e.ticker)["title"][e.name] = title_pos
        else:
            # the score is the decision; positions recorded even if empty
            # (ref :174-180); cutoff semantics: sub-threshold scores are 0
            if text_score.get(j, 0.0) > threshold:
                slot(e.ticker)["text"][e.name] = _find_positions_literal_fallback(
                    e.name, text
                )
            if title_score.get(j, 0.0) > threshold:
                slot(e.ticker)["title"][e.name] = _find_positions_literal_fallback(
                    e.name, title
                )
    return {t: v for t, v in per_ticker.items() if v["text"] or v["title"]}


def _get_col(row, *candidates, default=""):
    for c in candidates:
        if c in row and pd.notna(row[c]):
            return str(row[c])
    return default


def _refine_candidates(index: EntityIndex):
    """Fuzzy names the Myers bound kernel can handle: non-exact-upper,
    1..MAX_PATTERN bytes, pure ASCII (the bound is byte-level; multi-byte
    chars would break its soundness vs the char-level oracle).  Returns
    ``(name_indices, names, mask_tables)``, cached on the index (same
    lifetime as ``screen_tables`` — the tables depend only on the index,
    never on the chunk)."""
    cached = getattr(index, "_refine_tables", None)
    if cached is not None:
        return cached
    from advanced_scrapper_tpu.ops.editdist import MAX_PATTERN, build_pattern_masks

    ix, names = [], []
    for j, e in enumerate(index.entries):
        nb = e.name.encode("utf-8", "replace")
        if not e.is_exact_upper and 0 < len(nb) <= MAX_PATTERN and nb.isascii():
            ix.append(j)
            names.append(nb)
    out = (np.asarray(ix, dtype=np.int64), names, build_pattern_masks(names))
    index._refine_tables = out
    return out


class RefineController:
    """Measured race for the alignment-bound stage (r5, VERDICT r4 item 3).

    The r4 "auto" gate keyed on a 256-pair breakeven — the WRONG
    statistic: re-measured on the same CPU backend, the adversarial decoy
    corpus (~840 surviving pairs/batch) runs 2.2× FASTER with refine
    while the bench corpus (~4,200 pairs/batch — MORE pairs) runs 1.9×
    SLOWER; a pair-count threshold picks wrong in both directions, and it
    cost the driver-tracked matcher metric 38% in r4.  Whether the bound
    kernel pays depends on the prune yield and the host-vs-device cost
    ratio of the actual (backend, corpus) pair — knowable only by
    measurement, so the streaming path RACES the two modes: probe each
    mode once on real chunks, commit to the winner (refine must beat
    screen-only by 5% to win — ties go to the simpler mode), and re-RACE
    from scratch every ``PROBE_EVERY`` chunks so corpus drift can flip
    the verdict (a min kept forever would let a stale win pin a mode
    that has since degraded).  Within an epoch, per-mode cost is the MIN
    observed s/row — robust against pipeline-queue inflation, which only
    ever adds time.
    """

    PROBE_EVERY = 16
    WIN_MARGIN = 0.95

    def __init__(self):
        # locked: since the streaming path became a stage graph,
        # next_mode() runs in the screen stage's worker thread while
        # record() runs in the drain (caller) thread — an epoch reset
        # must never be observed half-applied
        self._lock = threading.Lock()
        self._best: dict[bool, float | None] = {False: None, True: None}
        self._chunks = 0
        self._default = False  # verdict carried across epoch resets

    def next_mode(self) -> bool:
        with self._lock:
            if self._best[False] is None:
                return False
            if self._best[True] is None:
                return True
            return self._verdict_locked()

    def record(self, mode: bool, seconds_per_row: float) -> None:
        with self._lock:
            self._chunks += 1
            if self._chunks % self.PROBE_EVERY == 0:
                # epoch boundary: carry the verdict as the default, re-race
                self._default = self._verdict_locked()
                self._best = {False: None, True: None}
            prev = self._best[mode]
            if prev is None or seconds_per_row < prev:
                self._best[mode] = seconds_per_row

    def verdict(self) -> bool:
        with self._lock:
            return self._verdict_locked()

    def _verdict_locked(self) -> bool:
        off, on = self._best[False], self._best[True]
        if off is None or on is None:
            return self._default  # mid-race: the last settled verdict
        return on < off * self.WIN_MARGIN


def _refine_batch(
    batch,
    got: np.ndarray,
    overlong,
    fuzzy_ix: np.ndarray,
    fuzzy_names: list,
    mask_tables,
    threshold: float,
    *,
    max_pairs: int = 1024,
) -> list[set | None]:
    """Per-row sets of name indices whose text-side score is device-proven
    ≤ threshold.  Non-ASCII texts pass through (byte/char mismatch).
    Zero surviving pairs → no device dispatch at all."""
    from advanced_scrapper_tpu.core.tokenizer import encode_batch
    from advanced_scrapper_tpu.ops.editdist import prune_mask_tables

    name_lens = np.array([len(n) for n in fuzzy_names], dtype=np.int64)
    pair_row: list[int] = []
    pair_k: list[int] = []
    for i, (text, _title, _d, _r) in enumerate(batch):
        if overlong[i] or not text or not text.isascii():
            continue
        # strictly longer only: equal-length pairs are never prunable under
        # rapidfuzz's bidirectional rule (see editdist.prune_mask_tables)
        sel = np.nonzero(got[i][fuzzy_ix] & (len(text) > name_lens))[0]
        pair_row.extend([i] * len(sel))
        pair_k.extend(sel.tolist())
    out: list[set | None] = [None] * len(batch)
    if not pair_row:
        return out
    row_ids = sorted(set(pair_row))
    pos = {r: k for k, r in enumerate(row_ids)}
    tok, ln = encode_batch([batch[r][0] for r in row_ids])
    from advanced_scrapper_tpu.obs import stages

    for start in range(0, len(pair_row), max_pairs):
        rows_s = pair_row[start : start + max_pairs]
        ks = pair_k[start : start + max_pairs]
        # pad the slice to a fixed pair count: the jitted kernel would
        # otherwise recompile for every distinct remainder size
        pad = max_pairs - len(rows_s)
        t_ix = np.array([pos[r] for r in rows_s] + [pos[rows_s[0]]] * pad)
        ks_p = np.array(ks + [ks[0]] * pad)
        t_slice, l_slice = tok[t_ix], ln[t_ix]
        # ledger instrumentation: the slice's jit args (gathered texts +
        # the per-pair mask gather) ARE this path's tile traffic — count
        # them as the two dominant puts plus the bound dispatch so the
        # packed path's 1+1 contract is a measured subtraction against
        # comparable legacy numbers
        stages.count_device_put(t_slice.nbytes, "matcher")
        stages.count_device_put(len(ks_p) * 256 * 4, "matcher")
        stages.count_dispatch("matcher")
        pruned = prune_mask_tables(
            mask_tables, t_slice, l_slice, ks_p, threshold
        )
        for r, k, p in zip(rows_s, ks, pruned):
            if p:
                if out[r] is None:
                    out[r] = set()
                out[r].add(int(fuzzy_ix[k]))
    return out


# -- packed single-dispatch screen tiles (the PR 9 plane) --------------------


def _screen_tile_rows(tile_bytes: int, width: int) -> int:
    """Full-tile row count for a screen width bucket: the byte budget
    divided by the row width, power-of-two bucketed, clamped to
    [16, 4096].  THE single source of the formula — the tile chunker and
    :func:`prewarm_screen` must draw from the same shape set, or
    prewarming silently compiles a disjoint set and defeats itself
    (the dedup encoder's `_tile_bs` lesson)."""
    bs = min(max(tile_bytes // max(width, 1), 16), 4096)
    return 1 << (int(bs).bit_length() - 1)


def _screen_rows_options(bs: int) -> list[int]:
    """Every row count the greedy tile chunker can emit for a width
    bucket: the full tile plus the descending power-of-two tail chunks
    (≥16; the last one zero-pads) — the O(log bs) shape set prewarm
    compiles (``core.tokenizer.tile_rows_options``, shared with the
    dedup tile plane)."""
    from advanced_scrapper_tpu.core.tokenizer import tile_rows_options

    return tile_rows_options(bs, 16)


def _screen_steps(index: EntityIndex, use_refine: bool):
    """The index's compiled packed screen step for one MODE — screen-only
    or fused screen+Myers-bound (``ops.match.make_screen_step``; the
    refine-race controller picks between these two modes, not between
    separate kernels).  Built lazily once per (index, mode) — the name
    tables constant-fold into the step, so streaming chunks never re-ship
    them (zero per-tile table traffic)."""
    cache = getattr(index, "_packed_steps", None)
    if cache is None:
        cache = index._packed_steps = {}
    key = bool(use_refine)
    step = cache.get(key)
    if step is None:
        from advanced_scrapper_tpu.obs import devprof, stages
        from advanced_scrapper_tpu.ops.match import make_screen_step

        with stages.timed("matcher_build"):
            refine = None
            if key:
                fuzzy_ix, _names, mask_tables = _refine_candidates(index)
                if len(fuzzy_ix):
                    masks, lens, ok = mask_tables
                    refine = (masks, lens, ok, fuzzy_ix)
                else:
                    # no refine candidates ⇒ the fused mode IS the
                    # screen-only step — alias it instead of compiling an
                    # identical kernel under a second jit closure (the
                    # recompile sentinel rides the alias too: one wrapped
                    # object, one jit cache)
                    step = cache[key] = _screen_steps(index, False)
                    return step
            # recompile sentinel (obs/devprof.py): every jit-cache miss
            # counts on astpu_jit_compiles_total{kernel=
            # "matcher_screen_step"} — prewarm_screen's compiles are the
            # expected counts, a steady-state increment is the stall the
            # prewarmed shape set exists to prevent
            step = devprof.instrument_jit(
                make_screen_step(index.screen_tables(), refine),
                "matcher_screen_step",
            )
        cache[key] = step
    return step


def _match_cfg() -> MatchConfig:
    """Env-resolved matcher knobs (``ASTPU_MATCH_*``) for direct
    ``match_chunk*`` callers that pass no explicit values — re-read per
    chunk (cheap: a handful of environ lookups) so tests and sweeps can
    flip knobs between calls."""
    from advanced_scrapper_tpu.config import from_env

    return from_env(MatchConfig, "match")


def _packed_screen(
    rows: list,
    index: EntityIndex,
    *,
    use_refine: bool,
    threshold: float,
    screen_block: int,
    tile_bytes: int,
    window: int,
    put_workers: int,
) -> tuple[list, list]:
    """Screen a chunk through the packed single-dispatch tile plane:
    width-bucketed rows → byte-budget tiles → ONE ``device_put`` + ONE
    fused jitted dispatch per tile, pipelined (encode/pack → h2d →
    dispatch) through the dispatch executor with a bounded in-flight
    window.  Returns ``(masks, text_prunes)`` in ``match_chunk_async``'s
    shapes; rows above ``screen_block`` never enter a tile (mask None =
    full host scan, counted in ``astpu_matcher_overlong_total``).

    Out-of-order tile arrival from the put pool never matters: each
    tile's rows carry their article owners (packed into the buffer,
    returned by the step), and the host scatter is per-row."""
    import jax

    from advanced_scrapper_tpu.obs import devprof, stages, telemetry
    from advanced_scrapper_tpu.ops.match import FLAG_REFINE_OK, MASK_TEXT_PRUNED
    from advanced_scrapper_tpu.ops.pack import pack_tile_planes
    from advanced_scrapper_tpu.core.tokenizer import bucket_widths, encode_batch
    from advanced_scrapper_tpu.pipeline.dispatch import (
        PipelinedDispatcher,
        resolve_dispatch_window,
    )

    n = len(rows)
    masks: list[np.ndarray | None] = [None] * n
    prunes: list[set | None] = [None] * n
    with stages.timed("matcher_screen"):
        raw = [
            (title + "\n" + text).encode("utf-8", "replace")
            for text, title, _, _ in rows
        ]
        lens = np.fromiter(map(len, raw), np.int64, count=n)
        title_len = np.array(
            [len(t.encode("utf-8", "replace")) for _, t, _, _ in rows],
            np.int32,
        )
        # per-char encoding ⇒ len(title\ntext) = len(title) + 1 + len(text)
        # exactly, so the text side never pays a second full-article encode
        text_len = (lens - title_len - 1).astype(np.int32)
        flags = np.array(
            [
                FLAG_REFINE_OK if (t and t.isascii()) else 0
                for t, _, _, _ in rows
            ],
            np.int32,
        )
        overlong = lens > screen_block
        n_overlong = int(overlong.sum())
        if n_overlong:
            telemetry.event_counter(
                "astpu_matcher_overlong_total",
                "articles above screen_block routed to the full host scan",
            ).inc(n_overlong)
        eligible = np.flatnonzero(~overlong)
    if eligible.size == 0:
        return masks, prunes
    widths = bucket_widths(
        lens[eligible], min_bucket=1024, max_bucket=screen_block
    )
    order = np.argsort(widths, kind="stable")
    sorted_w = widths[order]
    group_lo = np.flatnonzero(np.r_[True, sorted_w[1:] != sorted_w[:-1]])
    step = _screen_steps(index, use_refine)
    probe = getattr(index, "dispatch_probe", None)

    def tiles():
        for g, lo in enumerate(group_lo):
            hi = group_lo[g + 1] if g + 1 < len(group_lo) else len(order)
            idx = eligible[order[lo:hi]]
            w = int(sorted_w[lo])
            bs = _screen_tile_rows(tile_bytes, w)  # shared with prewarm
            start = 0
            while start < len(idx):
                remaining = len(idx) - start
                nrows = bs
                if remaining < bs:
                    nrows = 16
                    while nrows * 2 <= remaining:
                        nrows *= 2
                sel = idx[start : start + nrows]
                with stages.timed("matcher_screen"):
                    tok, dl = encode_batch(
                        [raw[j] for j in sel], block_len=w
                    )
                    own = sel.astype(np.int32)
                    if tok.shape[0] < nrows:
                        pad = nrows - tok.shape[0]
                        tok = np.concatenate(
                            [tok, np.zeros((pad, w), np.uint8)]
                        )
                        dl = np.concatenate([dl, np.zeros((pad,), np.int32)])
                        own = np.concatenate(
                            [own, np.full((pad,), -1, np.int32)]
                        )
                    tl = np.zeros((nrows,), np.int32)
                    ttl = np.zeros((nrows,), np.int32)
                    fl = np.zeros((nrows,), np.int32)
                    tl[: len(sel)] = text_len[sel]
                    ttl[: len(sel)] = title_len[sel]
                    fl[: len(sel)] = flags[sel]
                yield tok, dl, tl, ttl, fl, own, w
                start += nrows

    def pack(item):
        # plane order is the step's SCREEN_PLANES unpack contract
        tok, dl, tl, ttl, fl, own, w = item
        with stages.timed("matcher_screen"):
            buf = pack_tile_planes(tok, dl, tl, ttl, fl, own)
        return buf, tok.shape[0], w

    def put(item):
        buf, nrows, w = item
        t0 = time.perf_counter()
        with stages.timed("h2d"):
            dev = jax.device_put(buf)
        stages.count_device_put(buf.nbytes, "matcher")
        return dev, nrows, w, buf.nbytes, time.perf_counter() - t0

    def scatter(result) -> None:
        mask_dev, own_dev = result
        m = np.asarray(mask_dev)  # readback sync: waits for THIS tile only
        own = np.asarray(own_dev)
        keep = (m & 1).astype(bool)
        for local in range(m.shape[0]):
            a = int(own[local])
            if a >= 0:
                masks[a] = keep[local]
        if use_refine:
            for r, c in zip(*np.nonzero(m & MASK_TEXT_PRUNED)):
                a = int(own[r])
                if a < 0:
                    continue
                if prunes[a] is None:
                    prunes[a] = set()
                prunes[a].add(int(c))

    # Mask readback trails the dispatch loop by a bounded LAG (the
    # executor's own residency bound) instead of syncing per tile (the
    # legacy loop's stall) or deferring every tile to end-of-chunk: a
    # 20k-row chunk against a large entity index would otherwise hold
    # O(tiles) [rows, N] device masks at once.  Syncing a tile that is
    # `lag` dispatches behind costs ~nothing — it has almost surely
    # completed — so the pipeline stays full with device residency
    # capped at lag mask buffers.
    lag = resolve_dispatch_window(window, put_workers) + put_workers + 1
    results: list = []
    pipe = PipelinedDispatcher(
        tiles(),
        pack=pack,
        put=put,
        put_workers=put_workers,
        window=window,
        name="matcher.h2d",
    )
    try:
        for i, item in enumerate(pipe):
            dev, nrows, w, nbytes, put_s = item
            t0 = time.perf_counter()
            with stages.timed("matcher_screen"), devprof.dispatch_span(
                "matcher_screen_tile", rows=nrows, width=w
            ) as sp:
                # async dispatch; trailing tiles drain below
                out = step(dev, threshold, rows=nrows, width=w)
                sp.out = out
            stages.count_dispatch("matcher")
            results.append(out)
            if probe is not None:
                probe(
                    {
                        "tile": i,
                        "rows": nrows,
                        "width": w,
                        "h2d_bytes": nbytes,
                        "put_ms": round(put_s * 1e3, 3),
                        "dispatch_ms": round(
                            (time.perf_counter() - t0) * 1e3, 3
                        ),
                    }
                )
            if len(results) > lag:
                with stages.timed("matcher_screen"):
                    scatter(results.pop(0))
    finally:
        pipe.close()
    with stages.timed("matcher_screen"):
        for result in results:
            scatter(result)
    return masks, prunes


def _legacy_screen(
    rows: list,
    index: EntityIndex,
    *,
    use_refine: bool,
    threshold: float,
    screen_batch: int,
    screen_block: int,
) -> tuple[list, list]:
    """The pre-packed screen loop (``ASTPU_MATCH_PACKED=0``): fixed
    ``screen_batch`` batches, separate screen and Myers-bound dispatches.
    Kept byte-identical as the parity oracle and escape hatch, and
    INSTRUMENTED — its per-batch device traffic (4 array puts + 1 screen
    dispatch, plus the refine slices' transfers) lands in the same
    always-on counters as the packed path, so the launch-count drop is a
    measured subtraction, not prose."""
    import jax

    from advanced_scrapper_tpu.core.tokenizer import bucket_len, encode_batch
    from advanced_scrapper_tpu.obs import devprof, stages, telemetry
    from advanced_scrapper_tpu.ops.match import match_screen

    tables = index.screen_tables()
    fuzzy_ix, fuzzy_names, mask_tables = (
        _refine_candidates(index) if use_refine else (np.array([]), [], None)
    )
    masks: list[np.ndarray | None] = [None] * len(rows)
    text_prunes: list[set | None] = [None] * len(rows)
    n_overlong = 0
    for start in range(0, len(rows), screen_batch):
        batch = rows[start : start + screen_batch]
        with stages.timed("matcher_screen"):
            # bitmap over title+text; part lengths drive the soundness
            # bounds
            raw = [
                (title + "\n" + text).encode("utf-8", "replace")
                for text, title, _, _ in batch
            ]
            text_len = np.array(
                [len(t.encode("utf-8", "replace")) for t, _, _, _ in batch],
                np.int32,
            )
            title_len = np.array(
                [len(t.encode("utf-8", "replace")) for _, t, _, _ in batch],
                np.int32,
            )
            overlong = [len(r) > screen_block for r in raw]
            n_overlong += sum(overlong)
            # ``screen_block`` is a CAP, not the tile width: the batch
            # encodes at the longest article's power-of-two bucket, so a
            # 2 kB news corpus screens on 2 kB rows instead of paying the
            # 64 kB worst case (measured 88% of matcher wall time was
            # screening zero padding).  O(log) compiled screen shapes.
            blk = bucket_len(
                max(len(r) for r in raw), min_bucket=1024,
                max_bucket=screen_block,
            )
            tok, ln = encode_batch(raw, block_len=blk)
        # puts land in h2d ONLY (matching the packed path's put stage) —
        # nesting them inside matcher_screen would double-count transfer
        # time into the exact stage the packed-vs-legacy A/B compares
        with stages.timed("h2d"):
            tok_d = jax.device_put(tok)
            tl_d = jax.device_put(text_len)
            ttl_d = jax.device_put(title_len)
            ln_d = jax.device_put(ln)
        for arr in (tok, text_len, title_len, ln):
            stages.count_device_put(arr.nbytes, "matcher")
        with stages.timed("matcher_screen"), devprof.dispatch_span(
            "matcher_screen_legacy",
            rows=int(tok.shape[0]), width=int(tok.shape[1]),
        ) as sp:
            got = match_screen(
                tok_d, tl_d, ttl_d, ln_d, tables, threshold=threshold
            )
            sp.out = got
            stages.count_dispatch("matcher")
        for i in range(len(batch)):
            # articles longer than the screen block fall back to full scan
            masks[start + i] = None if overlong[i] else got[i]
        if len(fuzzy_ix):
            prunes = _refine_batch(
                batch, got, overlong, fuzzy_ix, fuzzy_names, mask_tables,
                threshold,
            )
            for i, pr in enumerate(prunes):
                text_prunes[start + i] = pr
    if n_overlong:
        telemetry.event_counter(
            "astpu_matcher_overlong_total",
            "articles above screen_block routed to the full host scan",
        ).inc(n_overlong)
    return masks, text_prunes


def prewarm_screen(
    index: EntityIndex,
    *,
    use_refine: bool | None = None,
    threshold: float = 95.0,
    screen_block: int = 1 << 16,
    tile_bytes: int | None = None,
) -> int:
    """Compile the packed screen-step shape set ahead of the first chunk
    (the matcher twin of ``NearDupEngine.prewarm``): every width bucket
    from 1024 to ``screen_block`` × its O(log bs) tile row options, for
    the screen-only mode, the fused mode, or both (``use_refine=None``
    compiles both — the refine race will dispatch whichever wins).
    Returns the number of shape variants compiled.  With
    ``ASTPU_COMPILE_CACHE`` set the compiles persist across processes
    and later prewarms are cache loads."""
    import jax.numpy as jnp

    from advanced_scrapper_tpu.core.mesh import maybe_enable_compile_cache
    from advanced_scrapper_tpu.ops.match import SCREEN_PLANES
    from advanced_scrapper_tpu.ops.pack import packed_nbytes

    maybe_enable_compile_cache()
    if tile_bytes is None:
        tile_bytes = _match_cfg().screen_tile_bytes
    widths = []
    w = 1024
    while w < screen_block:
        widths.append(w)
        w *= 2
    widths.append(screen_block)
    modes = (False, True) if use_refine is None else (bool(use_refine),)
    compiled = 0
    warmed: set[int] = set()
    for mode in modes:
        step = _screen_steps(index, mode)
        if id(step) in warmed:
            continue  # fused mode aliased to screen-only (no candidates)
        warmed.add(id(step))
        for w in widths:
            for rows in _screen_rows_options(_screen_tile_rows(tile_bytes, w)):
                packed = jnp.zeros(
                    (packed_nbytes(rows, w, SCREEN_PLANES),), jnp.uint8
                )
                mask, _own = step(packed, threshold, rows=rows, width=w)
                mask.block_until_ready()
                compiled += 1
    return compiled


def match_chunk_async(
    chunk: pd.DataFrame,
    index: EntityIndex,
    *,
    use_screen: bool = True,
    use_refine: bool | str = "auto",
    screen_batch: int = 128,
    screen_block: int = 1 << 16,
    threshold: float = 95.0,
    pool=None,
    packed: bool | None = None,
    screen_tile_bytes: int | None = None,
    dispatch_window: int | None = None,
    screen_put_workers: int | None = None,
):
    """Screen + submit a frame NOW; return a zero-arg ``collect()`` whose
    call yields :func:`match_chunk`'s result.

    With a pool, the verify slices are already in flight when this
    returns, so a streaming caller (``run_matcher``) can screen chunk
    i+1 on the device while chunk i's verify work runs in the pool —
    the reference's own overlap (its ``mp.Pool`` never sits idle between
    20k-row chunks, ``match_keywords.py:227-238``).  Without a pool,
    ``collect()`` does the verify work serially when called.
    """
    # identity checks, not `in (True, False, "auto")`: 1 == True would
    # slip through equality and silently demote a forced-on request to auto
    if not (use_refine is True or use_refine is False or use_refine == "auto"):
        raise ValueError(f"use_refine must be True/False/'auto', got {use_refine!r}")
    if use_refine is True and not use_screen:
        # refine lives inside the screen path; silently no-opping here would
        # betray a direct caller's explicit request (previously this guard
        # lived only in run_matcher).  "auto" is opportunistic, not a
        # request — without the screen it simply never engages.
        raise ValueError("use_refine requires use_screen (see DESIGN.md §4)")
    if use_refine == "auto":
        # "auto" defers to a RefineController verdict measured on THIS
        # (backend, corpus) pair — run_matcher's streaming race attaches
        # one to the index; without a measurement refine stays off (the
        # r4 pair-count gate guessed, and guessed wrong; see
        # RefineController)
        ctrl = getattr(index, "refine_controller", None)
        use_refine = ctrl.verdict() if ctrl is not None else False

    from advanced_scrapper_tpu.obs import telemetry, trace

    m_articles = telemetry.counter(
        "astpu_matcher_articles_total", "articles entering the matcher"
    )
    m_matches = telemetry.counter(
        "astpu_matcher_matches_total", "(ticker, article) matches produced"
    )
    tid = trace.new_trace_id()

    rows = []
    # plain dicts, not Series: ~100 µs/row cheaper to build, identical
    # mapping access in _get_col, and far cheaper to pickle to pool workers
    for row in chunk.to_dict("records"):
        text = _get_col(row, "article_text", "article")
        title = _get_col(row, "title")
        raw_date = _get_col(row, "date_time", "datetime", default="")
        try:
            adate = dateparser.parse(raw_date) if raw_date else None
        except (ValueError, OverflowError, dateparser.ParserError):
            adate = None
        rows.append((text, title, adate, row))

    m_articles.inc(len(rows))
    masks: list[np.ndarray | None] = [None] * len(rows)
    text_prunes: list[set | None] = [None] * len(rows)
    if use_screen and index.entries:
        # knob resolution: explicit args win, else the ASTPU_MATCH_* env
        # (run_matcher passes its MatchConfig fields through explicitly)
        if None in (
            packed, screen_tile_bytes, dispatch_window, screen_put_workers
        ):
            _cfg = _match_cfg()
            packed = _cfg.packed if packed is None else packed
            if screen_tile_bytes is None:
                screen_tile_bytes = _cfg.screen_tile_bytes
            if dispatch_window is None:
                dispatch_window = _cfg.dispatch_window
            if screen_put_workers is None:
                screen_put_workers = _cfg.put_workers
        if not screen_put_workers:
            from advanced_scrapper_tpu.core.mesh import auto_h2d_workers

            screen_put_workers = auto_h2d_workers()
        t_screen = time.perf_counter()
        if packed:
            # the PR 9 plane: byte-budget width-bucketed tiles, ONE packed
            # put + ONE fused screen(+bound) dispatch per tile, pipelined
            # (retired screen_batch is ignored here — MIGRATION.md)
            masks, text_prunes = _packed_screen(
                rows,
                index,
                use_refine=bool(use_refine),
                threshold=threshold,
                screen_block=screen_block,
                tile_bytes=screen_tile_bytes,
                window=dispatch_window,
                put_workers=screen_put_workers,
            )
        else:
            masks, text_prunes = _legacy_screen(
                rows,
                index,
                use_refine=bool(use_refine),
                threshold=threshold,
                screen_batch=screen_batch,
                screen_block=screen_block,
            )
        if trace.RECORDER.active:
            trace.record(
                "span",
                "matcher.screen",
                trace=tid,
                articles=len(rows),
                dur_ms=round((time.perf_counter() - t_screen) * 1e3, 3),
            )

    if pool is not None and len(rows) > 1:
        # ship (text, title, date, row-INDEX) out; the full row record stays
        # here and is re-attached on return (half the IPC volume)
        light = [(t, ti, d, i) for i, (t, ti, d, _r) in enumerate(rows)]
        n_slices = min(getattr(pool, "_max_workers", 4), len(rows))
        bounds = np.linspace(0, len(rows), n_slices + 1).astype(int)
        futures = [
            pool.submit(
                _verify_slice,
                light[lo:hi], masks[lo:hi], text_prunes[lo:hi], threshold,
            )
            for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo
        ]

        def collect():
            from advanced_scrapper_tpu.obs import stages

            out = []
            with stages.timed("matcher_verify"), trace.span(
                "matcher.verify", trace=tid, articles=len(rows)
            ):
                for f in futures:  # slice order == row order
                    out.extend(
                        (ticker, m, rows[i][3]) for ticker, m, i in f.result()
                    )
            m_matches.inc(len(out))
            return out

        collect.futures = futures  # introspectable: the in-flight slices
        return collect

    def collect():
        from advanced_scrapper_tpu.obs import stages

        out = []
        with stages.timed("matcher_verify"), trace.span(
            "matcher.verify", trace=tid, articles=len(rows)
        ):
            for (text, title, adate, row), mask, pruned in zip(
                rows, masks, text_prunes
            ):
                matches = match_article(
                    text, title, adate, index, mask, threshold, pruned
                )
                for ticker, m in matches.items():
                    out.append((ticker, m, row))
        m_matches.inc(len(out))
        return out

    return collect


def match_chunk(
    chunk: pd.DataFrame,
    index: EntityIndex,
    *,
    use_screen: bool = True,
    use_refine: bool | str = "auto",
    screen_batch: int = 128,
    screen_block: int = 1 << 16,
    threshold: float = 95.0,
    pool=None,
    packed: bool | None = None,
    screen_tile_bytes: int | None = None,
    dispatch_window: int | None = None,
    screen_put_workers: int | None = None,
) -> list[tuple[str, dict, dict]]:
    """Match a frame of articles → [(ticker, matches, row_record), …].

    Accepts both the reference dataset schema (``article_text``/``date_time``)
    and this framework's scraper schema (``article``/``datetime``).

    ``pool`` (an executor from :func:`make_verify_pool`) fans the host-side
    exact-verify stage out across processes — the successor of the
    reference's ``np.array_split`` × ``mp.Pool.starmap(cpu_count)``
    (``match_keywords.py:231-238``).  The device screen always runs in THIS
    process (one device context); only the CPU verify work ships out.
    Output order is identical with and without a pool.
    """
    return match_chunk_async(
        chunk,
        index,
        use_screen=use_screen,
        use_refine=use_refine,
        screen_batch=screen_batch,
        screen_block=screen_block,
        threshold=threshold,
        pool=pool,
        packed=packed,
        screen_tile_bytes=screen_tile_bytes,
        dispatch_window=dispatch_window,
        screen_put_workers=screen_put_workers,
    )()


# -- verify-stage process pool (ref match_keywords.py:231-238) ---------------

_WORKER_INDEX: EntityIndex | None = None


def _verify_worker_init(processed: dict) -> None:
    """Build the worker's EntityIndex ONCE (not per slice)."""
    global _WORKER_INDEX
    _WORKER_INDEX = EntityIndex(processed)


def _warm_noop() -> bool:
    return True


def _verify_slice(rows, masks, prunes, threshold: float):
    """Run the host exact-verify rules over one row slice (no jax, no
    device: masks/prunes were computed by the screen in the parent).
    ``rows`` carry row INDICES, echoed back for parent-side re-attach."""
    index = _WORKER_INDEX
    out = []
    for (text, title, adate, row_ix), mask, pruned in zip(rows, masks, prunes):
        matches = match_article(text, title, adate, index, mask, threshold, pruned)
        for ticker, m in matches.items():
            out.append((ticker, m, row_ix))
    return out


@contextmanager
def _scrubbed_axon_env():
    """Temporarily drop the axon plugin's trigger vars.

    A fresh interpreter (spawn, or the forkserver's server process) re-runs
    the axon sitecustomize, which dials the TPU tunnel whenever
    ``PALLAS_AXON_POOL_IPS`` is set — and can hang forever on a dead
    tunnel.  Verify workers are jax-free host code, so any child
    interpreter started for them gets the trigger vars scrubbed."""
    saved = {
        k: os.environ.pop(k)
        for k in list(os.environ)
        if k.startswith("PALLAS_AXON")
    }
    try:
        yield
    finally:
        os.environ.update(saved)


def make_verify_pool(index: EntityIndex, workers: int | None = None):
    """ProcessPoolExecutor for the exact-verify stage, or None for ≤ 1
    worker.  The entity data ships once via the initializer, not per chunk.

    Start method: **forkserver**, fork-safe by construction (VERDICT r3
    item 7).  jax's fork warning flags ``os.fork()`` in a process whose
    (jax-internal) locks may be mid-acquire; with forkserver, every worker
    is forked from the forkserver's own server process — a fresh
    interpreter that never imports jax (worker code is host-only
    re/native/dateutil; ``ops.match`` device imports are lazy and live in
    the parent's screen stage).  No fork ever happens in a jax-threaded
    process, no matter when the pool is created or how imports evolve.
    The server interpreter is started under a scrubbed axon env so its
    startup can't dial a dead TPU tunnel (see ``_scrubbed_axon_env``)."""
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor, wait

    if workers is None or workers == 0:  # 0 = auto, matching cfg.verify_workers
        workers = os.cpu_count() or 1
    if workers <= 1:
        return None
    try:
        ctx = mp.get_context("forkserver")
    except ValueError:  # non-POSIX (no fork at all): spawn, same env scrub
        ctx = mp.get_context("spawn")
    with _scrubbed_axon_env():
        if ctx.get_start_method() == "forkserver":
            # start the server process NOW, while the trigger vars are
            # scrubbed; all later worker forks come from this process
            from multiprocessing import forkserver

            forkserver.ensure_running()
        pool = ProcessPoolExecutor(
            max_workers=workers,
            mp_context=ctx,
            initializer=_verify_worker_init,
            initargs=(index.processed,),
        )
        # Executors create workers lazily on first submit; warm every
        # worker now so spawn-mode children also start under the scrub
        # (forkserver children are safe regardless — their forks come
        # from the already-running jax-free server).
        warm = [pool.submit(_warm_noop) for _ in range(workers)]
        wait(warm)
        dead = next((f.exception() for f in warm if f.exception()), None)
        if dead is not None:
            # container/sandbox hosts that refuse worker processes must
            # degrade to inline verify, not poison every later submit
            import sys

            print(
                "verify pool unavailable "
                f"({type(dead).__name__}: {dead}); verifying inline",
                file=sys.stderr,
            )
            pool.shutdown(wait=False, cancel_futures=True)
            return None
    return pool


# -- output writing (ref :128-146, :195-217) --------------------------------


def append_match(out_dir: str, ticker: str, matches: dict, row) -> bool:
    raw_date = _get_col(row, "date_time", "datetime")
    try:
        ts = int(dateparser.parse(raw_date).timestamp())
    except Exception:
        print(f"skipping row with unparseable date_time: {raw_date!r}")
        return False
    record = {
        "time_unix": ts,
        "date_time": raw_date,
        "text_matches": json.dumps(matches["text"]),
        "title_matches": json.dumps(matches["title"]),
        "title": _get_col(row, "title"),
        "url": _get_col(row, "url"),
        "source": _get_col(row, "source"),
        "source_url": _get_col(row, "source_url"),
        "article_text": _get_col(row, "article_text", "article"),
    }
    path = os.path.join(out_dir, f"{ticker}_match.csv")
    header = not os.path.exists(path)
    pd.DataFrame([record]).to_csv(path, mode="a", index=False, header=header)
    return True


def sort_matched_csv(path: str) -> None:
    """Final per-file time sort (ref :195-217)."""
    try:
        df = pd.read_csv(path)
        if "time_unix" not in df.columns:
            df["date_time"] = df["date_time"].apply(dateparser.parse)
            df["time_unix"] = df["date_time"].apply(lambda x: int(x.timestamp()))
        df = df.sort_values("time_unix", ascending=True)
        df["time_unix"] = df["time_unix"].astype(int)
        df.to_csv(path, index=False)
    except Exception as e:
        print(f"Error processing {path}: {e}")


def run_matcher(
    cfg: MatchConfig,
    *,
    use_screen: bool | None = None,
    use_refine: bool | str = "auto",
    articles_csv: str | None = None,
    workers: int | None = None,
) -> int:
    """CLI entry: full matching run (ref ``__main__`` :220-246).

    The verify stage fans out over ``workers`` processes (default
    ``cfg.verify_workers``; 0 = ``os.cpu_count()``, the reference's pool
    width) — one pool for the whole run, created BEFORE the screen touches
    the device so fork never duplicates an active device context.  CSV
    writing stays in this process: single-writer by construction, unlike
    the reference's lock-free multi-process appends
    (``match_keywords.py:128-146``, a known race designed out here).
    """
    articles_csv = articles_csv or cfg.articles_csv
    if not os.path.exists(articles_csv):
        print(f"Articles CSV '{articles_csv}' not found.")
        return 1
    index = EntityIndex.from_info_dir(cfg.info_dir)
    out_dir = f"{cfg.source_name}{cfg.out_dir_suffix}"
    os.makedirs(out_dir, exist_ok=True)
    use_screen = cfg.use_tpu if use_screen is None else use_screen
    if use_refine is True and not use_screen:
        # "auto" is opportunistic and simply never engages without the
        # screen; only an explicit always-on request conflicts
        raise ValueError("use_refine requires use_screen (see DESIGN.md §4)")
    if workers is None:
        workers = cfg.verify_workers
    if cfg.prewarm and cfg.packed and use_screen and index.entries:
        # compile the screen-step shape set before the first chunk (the
        # NearDupEngine.prewarm twin; pointless under the legacy loop,
        # which never dispatches the packed step).  Under "auto" both
        # modes compile so the refine race can flip without a mid-stream
        # compile stall; a forced mode prewarms only the mode that can
        # ever dispatch.
        prewarm_screen(
            index,
            use_refine=None if use_refine == "auto" else bool(use_refine),
            threshold=cfg.fuzzy_threshold,
            tile_bytes=cfg.screen_tile_bytes,
        )
    pool = make_verify_pool(index, workers)  # 0/None normalise to cpu_count
    n_matches = 0
    # the streaming race that calibrates "auto" for THIS backend+corpus:
    # per-chunk screen+verify wall per row feeds the controller, which
    # probes each mode once and then exploits the measured winner
    # no controller without the screen: refine cannot engage there, and a
    # raw "auto" string must never reach controller.record
    controller = (
        RefineController() if use_refine == "auto" and use_screen else None
    )
    if controller is not None:
        index.refine_controller = controller

    def drain(item) -> None:
        nonlocal n_matches
        collect, mode, screen_s, nrows = item
        t0 = time.perf_counter()
        for ticker, matches, row in collect():
            if append_match(out_dir, ticker, matches, row):
                n_matches += 1
        if controller is not None and nrows:
            controller.record(mode, (screen_s + time.perf_counter() - t0) / nrows)

    # screen→verify as a stage graph: the single-worker ``screen`` stage
    # reads a chunk and submits its device screen + pool verify slices;
    # the capacity-1 ``screened`` edge bounds the window at ≤3 resident
    # chunks (one draining, one buffered, one the stage just screened
    # before blocking on put — one more than the old deque's 2, traded
    # for the screen never idling), and the drain stays in THIS thread so
    # CSV appends remain single-writer, in chunk order (FIFO edge + one
    # worker ⇒ order preserved by construction).
    from advanced_scrapper_tpu.runtime import DONE, StageGraph

    chunks = pd.read_csv(articles_csv, chunksize=cfg.chunk_size)

    def read_next():
        try:
            return next(chunks)
        except StopIteration:
            return DONE

    def screen(chunk):
        mode = (
            controller.next_mode()
            if controller is not None and use_screen
            else use_refine
        )
        t0 = time.perf_counter()
        collect = match_chunk_async(
            chunk,
            index,
            use_screen=use_screen,
            use_refine=mode,
            threshold=cfg.fuzzy_threshold,
            pool=pool,
            packed=cfg.packed,
            screen_tile_bytes=cfg.screen_tile_bytes,
            dispatch_window=cfg.dispatch_window,
            screen_put_workers=cfg.put_workers,
        )
        return (collect, mode, time.perf_counter() - t0, len(chunk))

    try:
        if pool is None:
            # serial mode keeps its deliberate single-chunk residency
            # bound: collect() is lazy caller-thread work with no overlap
            # to gain, so screening ahead would only double peak memory
            while True:
                chunk = read_next()
                if chunk is DONE:
                    break
                drain(screen(chunk))
        else:
            graph = StageGraph("matcher")
            screened = graph.edge("screened", capacity=1)
            graph.stage("screen", source=read_next, fn=screen, out_edge=screened)
            graph.start()
            try:
                for item in screened:
                    drain(item)
                if graph.error is not None:
                    raise graph.error  # the original screen-stage exception
            finally:
                graph.stop()
                graph.join(timeout=30, raise_error=False)
    finally:
        if pool is not None:
            pool.shutdown()
        # detach the controller: it holds a verdict measured on THIS
        # backend+corpus, and a later direct match_chunk(..., 'auto')
        # against the shared index must fall back to the measured-safe
        # off default instead of silently reusing a stale measurement
        if controller is not None and getattr(
            index, "refine_controller", None
        ) is controller:
            del index.refine_controller
    for f in os.listdir(out_dir):
        sort_matched_csv(os.path.join(out_dir, f))
    print(f"Matching complete: {n_matches} ticker-article matches → {out_dir}/")
    return 0
