"""L1 alternative engine: asyncio CDX harvester (the Scrapy-slot filler).

The reference kept a second harvester built on an async crawler framework
(``/root/reference/experiental/yahoo_links_scrapy.py`` — a Scrapy spider yielding the
same 1,444 prefix queries with identical shard-skip logic, :20-28) beside
the threaded Selenium one.  This module fills that slot TPU-era-style:
the same shard enumeration, resume semantics, normalisation chain and
BYTE-IDENTICAL shard files as ``pipeline/harvest.py`` (both engines call
``persist_shard``), but driven by a single-threaded asyncio event loop
with semaphore-bounded concurrency — the concurrency model Scrapy's
Twisted reactor provided, without a second framework dependency.

Engine choice is an operational trade, not a capability one:

- ``threads`` (default): one transport per worker thread — required when
  the transport is a real browser (Selenium/wire client), which cannot
  be awaited;
- ``async``: one aiohttp session, hundreds of in-flight HTTP requests on
  one thread — the right shape when archive.org is the bottleneck and
  plain HTTP suffices (the Scrapy experiment's premise).  Degrades to a
  thread-wrapped sync transport when aiohttp is unavailable.

Both funnel into the same ``merge_shards`` TPU-routed exact dedup.
"""

from __future__ import annotations

import asyncio
import os
from typing import Awaitable, Callable

from advanced_scrapper_tpu.config import HarvestConfig
from advanced_scrapper_tpu.pipeline.harvest import (
    cdx_query_url,
    merge_shards,
    persist_shard,
    shard_prefixes,
)

#: browser-ish UA, same contract as net.transport.RequestsTransport
from advanced_scrapper_tpu.net.transport import USER_AGENT


def _default_fetch() -> Callable[[str], Awaitable[str]]:
    """aiohttp-backed fetch; falls back to the sync transport wrapped in
    ``asyncio.to_thread`` so ``--engine async`` never hard-fails."""
    try:
        import aiohttp
    except ImportError:
        import threading

        from advanced_scrapper_tpu.net.transport import RequestsTransport

        # one transport PER to_thread worker thread: requests.Session is
        # not thread-safe, and the threaded engine's one-transport-per-
        # worker invariant must hold here too
        local = threading.local()
        transports: list[RequestsTransport] = []
        reg_lock = threading.Lock()

        def fetch_in_thread(url: str) -> str:
            t = getattr(local, "t", None)
            if t is None:
                t = local.t = RequestsTransport(timeout=30.0)
                with reg_lock:
                    transports.append(t)
            return t.fetch(url)

        async def fetch_sync(url: str) -> str:
            return await asyncio.to_thread(fetch_in_thread, url)

        def close_all() -> None:
            for t in transports:
                t.close()

        fetch_sync.close = close_all  # type: ignore[attr-defined]
        return fetch_sync

    session: dict = {}

    async def fetch(url: str) -> str:
        if "s" not in session:
            session["s"] = aiohttp.ClientSession(
                headers={"User-Agent": USER_AGENT},
                timeout=aiohttp.ClientTimeout(total=30.0),
            )
        async with session["s"].get(url) as resp:
            resp.raise_for_status()
            return await resp.text()

    async def close() -> None:
        if "s" in session:
            await session["s"].close()

    fetch.aclose = close  # type: ignore[attr-defined]
    return fetch


async def harvest_shards_async(
    cfg: HarvestConfig,
    *,
    fetch: Callable[[str], Awaitable[str]] | None = None,
    concurrency: int | None = None,
) -> int:
    """Sweep all pending shards with bounded async concurrency; returns
    the number of shards that succeeded.  ``fetch`` is an injectable
    ``async (url) -> str`` (tests use a local fixture server / closure).
    Parsing+persist runs in worker threads (``asyncio.to_thread``) so a
    large shard's pandas parse never stalls the event loop's I/O."""
    os.makedirs(cfg.shard_dir, exist_ok=True)
    prefixes = shard_prefixes(cfg.shard_dir)
    if not prefixes:
        return 0
    owns = fetch is None
    if fetch is None:
        fetch = _default_fetch()
    sem = asyncio.Semaphore(concurrency or max(1, cfg.num_workers))
    done = 0

    async def one(prefix: str) -> bool:
        url = cdx_query_url(prefix, cfg)
        try:
            # the semaphore is held across fetch AND persist: a fetched
            # page only releases its slot once it is on disk, so the
            # number of pages resident in memory is bounded by the
            # concurrency (a persist stage falling behind on a slow disk
            # can no longer balloon RSS with completed fetches; persist
            # still runs in a worker thread, so the event loop keeps
            # serving the other slots' I/O)
            async with sem:
                page = await fetch(url)
                await asyncio.to_thread(persist_shard, prefix, page, cfg)
            return True
        except Exception as e:
            # same per-shard containment as the threaded engine: a
            # failed shard logs, leaves NO checkpoint, and the sweep
            # continues (resume retries it next run)
            print(f"Error scraping {url}: {e}")
            return False

    try:
        for ok in await asyncio.gather(*(one(p) for p in prefixes)):
            done += int(ok)
    finally:
        if owns:
            closer = getattr(fetch, "aclose", None)
            if closer is not None:
                await closer()
            else:
                sync_close = getattr(fetch, "close", None)
                if sync_close is not None:
                    sync_close()
    return done


def run_harvest_async(
    cfg: HarvestConfig,
    *,
    fetch: Callable[[str], Awaitable[str]] | None = None,
    concurrency: int | None = None,
    use_tpu: bool = True,
) -> int:
    """CLI entry: async shard sweep + the same TPU-routed merge."""
    n = asyncio.run(
        harvest_shards_async(cfg, fetch=fetch, concurrency=concurrency)
    )
    print(f"Async harvest: {n} shards fetched")
    merge_shards(cfg, use_tpu=use_tpu)
    return 0
