"""Streaming CPU→TPU feed: native host batcher → prefetch → device kernels.

The async feeding architecture SURVEY.md §7 calls the hard part ("the host
must tokenize+batch faster than the device consumes"): producers push raw
documents into the C++ :class:`cpu.hostbatch.HostBatcher`; a staging stage
pops fixed-shape tiles and ``jax.device_put``\\ s them ahead of use (depth-2
double buffering), so batch assembly, H2D transfer, and device compute
overlap.  Tags (uint64, caller-chosen) ride along so results map back to
records without the host ever re-ordering documents.

Since the stage-graph runtime landed, the staging path IS a graph: the
``stage`` stage (N pull workers) feeds a runtime-owned ``staged`` edge
whose capacity is the prefetch depth — backpressure, close propagation,
error fan-out, depth/stall telemetry and the crash drain-snapshot all come
from ``advanced_scrapper_tpu.runtime`` instead of a hand-rolled
thread/queue/sentinel protocol (MIGRATION.md maps the retired knobs).

This is the firehose path: documents truncate at the feed block length
(matching the queue's fixed row shape).  For full blockwise coverage of
very long texts use :class:`pipeline.dedup.NearDupEngine` directly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterable, Iterator

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import make_params
from advanced_scrapper_tpu.cpu.hostbatch import HostBatcher
from advanced_scrapper_tpu.ops.lsh import band_keys
from advanced_scrapper_tpu.ops.minhash import minhash_signatures
from advanced_scrapper_tpu.runtime import DONE, RETRY, StageGraph


def resolve_prefetch_depth(depth: int | None) -> int:
    """Effective prefetch depth (device-side batches staged ahead of use):
    explicit ``depth`` wins, else ``ASTPU_FEED_PREFETCH``, else 2 (double
    buffering: one tile on device computing, one staging behind it)."""
    # <= 0 (explicit or via env, incl. "0") means "the default" — a
    # non-positive depth would make the staging edge UNBOUNDED
    if depth is not None and depth > 0:
        return depth
    env = int(os.environ.get("ASTPU_FEED_PREFETCH") or 0)
    return env if env > 0 else 2


class DeviceFeed:
    """Prefetching consumer of a :class:`HostBatcher`, run as a stage graph.

    The ``stage`` stage's workers pop host tiles and place them on device;
    the runtime's ``staged`` edge keeps up to ``depth`` batches in flight.
    Iterate to receive ``(n, tokens_dev, lengths_dev, tags)`` tuples;
    iteration ends when the batcher is closed and drained.

    Staging discipline: pops wait (up to ``poll_timeout_ms``) until a FULL
    tile's worth of documents is queued (``min_fill=batch_size``).  Without
    it, a consumer whose dispatch is async races ahead of the producer and
    pops whatever partial chunk just landed — and every partial tile still
    pays a full-shape device kernel (measured: the stream regime was
    dispatching ~6× the kernels it needed, r05's 0.15× gap vs the uniform
    ceiling).  A timeout or a closed queue still yields partial tiles, so a
    genuinely slow producer degrades gracefully instead of starving the
    device; ``min_fill=1`` restores the legacy pop-on-first-doc behaviour.
    """

    def __init__(
        self,
        batcher: HostBatcher,
        batch_size: int,
        *,
        depth: int | None = None,
        sharding=None,
        poll_timeout_ms: int = 200,
        workers: int | None = None,
        min_fill: int | None = None,
    ):
        """``workers > 1`` runs several pull workers (pop→device_put): on a
        transport whose per-put round trip serializes (the tunneled dev
        chip), concurrent puts overlap that latency.  Batches may then
        arrive out of submission order — safe for the dedup path, where
        every batch is independent and tags ride with their batch.
        ``None``/0 = the transport default (``core.mesh.auto_h2d_workers``).
        ``depth`` ``None``/0 = ``ASTPU_FEED_PREFETCH`` (default 2)."""
        import jax

        if not workers:
            from advanced_scrapper_tpu.core.mesh import auto_h2d_workers

            workers = auto_h2d_workers()

        self.batcher = batcher
        self.batch_size = batch_size
        self.sharding = sharding
        self.poll_timeout_ms = poll_timeout_ms
        self.min_fill = batch_size if min_fill is None else min_fill
        self._jax = jax
        # hot-loop setup hoisted out of _pull (it runs once per tile AND
        # once per empty poll): sharding specs and the obs module refs
        self._tok_spec = self._len_spec = None
        if sharding is not None:
            self._tok_spec, self._len_spec = sharding
        from advanced_scrapper_tpu.obs import stages as _stages
        from advanced_scrapper_tpu.obs import trace as _trace

        self._stages = _stages
        self._trace = _trace
        self._graph = StageGraph("feed")
        self._out = self._graph.edge("staged", resolve_prefetch_depth(depth))
        self._instrument()
        self._graph.stage(
            "stage",
            source=self._pull,
            out_edge=self._out,
            workers=max(1, workers),
        )
        self._graph.start()

    _seq_lock = threading.Lock()
    _seq = 0

    def _instrument(self) -> None:
        """Telemetry handles, fetched once (no-ops when disabled).  Queue
        depth / arena occupancy / rejected pushes export as CALLBACK gauges
        read at scrape time — the feed loop itself never samples them —
        and the per-batch pull loop owns the once-orphaned ``StepTimer``
        so ``summary()`` is reachable from production code (and mirrors
        into ``astpu_feed_step_seconds``).  The staged edge additionally
        exports the runtime's own depth/stall series
        (``astpu_edge_*{graph="feed"}``)."""
        from advanced_scrapper_tpu.obs import telemetry
        from advanced_scrapper_tpu.obs.profiler import StepTimer

        with DeviceFeed._seq_lock:
            feed_id = str(DeviceFeed._seq)
            DeviceFeed._seq += 1
        self._m_batches = telemetry.counter(
            "astpu_feed_batches_total", "tiles popped and staged on device"
        )
        self._m_docs = telemetry.counter(
            "astpu_feed_docs_total", "documents staged on device"
        )
        self._m_partial = telemetry.counter(
            "astpu_feed_partial_tiles_total",
            "tiles dispatched below batch_size (timeout/close drains)",
        )
        self._m_fill = telemetry.gauge(
            "astpu_feed_fill_ratio", "last tile's rows / batch_size", feed=feed_id
        )
        self.timer = StepTimer(
            histogram=telemetry.histogram(
                "astpu_feed_step_seconds", "pop→device_put cycle latency"
            )
        )
        telemetry.gauge_fn(
            "astpu_feed_queue_depth",
            lambda feed: feed.batcher.size(),
            owner=self,
            help="documents buffered in the host batcher",
            feed=feed_id,
        )
        telemetry.gauge_fn(
            "astpu_feed_arena_used_bytes",
            lambda feed: feed.batcher.arena_used(),
            owner=self,
            help="host batcher arena occupancy",
            feed=feed_id,
        )
        telemetry.gauge_fn(
            "astpu_feed_rejected_pushes",
            lambda feed: feed.batcher.stats()["rejected"],
            owner=self,
            help="pushes rejected by doc/arena backpressure",
            feed=feed_id,
        )
        telemetry.gauge_fn(
            "astpu_feed_staged_depth",
            lambda feed: feed._out.qsize(),
            owner=self,
            help="device-staged tiles awaiting the consumer",
            feed=feed_id,
        )

    def summary(self) -> dict:
        """Rolling per-tile step latency/throughput (``StepTimer.summary``)."""
        return self.timer.summary()

    def _put_device(self, arr: np.ndarray, spec=None):
        if self.sharding is not None and spec is not None:
            return self._jax.device_put(arr, spec)
        return self._jax.device_put(arr)

    def _pull(self):
        """One pop→device_put cycle: the ``stage`` stage's source.  Shared
        by every pull worker (the C++ batcher is MPMC-safe); returns a
        staged tuple, :data:`RETRY` on an empty poll, or :data:`DONE` once
        the batcher is closed and drained."""
        tok_spec, len_spec = self._tok_spec, self._len_spec
        stages, trace = self._stages, self._trace

        t0 = time.perf_counter()
        # host tile assembly (pop+memcpy); a slow producer's waits land
        # here too — "the host couldn't feed the device" is exactly what
        # this stage exists to expose
        with stages.timed("encode"):
            n, tok, lens, tags = self.batcher.pop_batch(
                self.batch_size,
                timeout_ms=self.poll_timeout_ms,
                min_fill=self.min_fill,
            )
        if n == 0:
            # 0 rows = timeout (retry) or closed-and-drained (done);
            # close() is one-way so this check is race-free.
            if self.batcher.closed() and self.batcher.size() == 0:
                return DONE
            return RETRY
        with stages.timed("h2d"):
            t_dev = self._put_device(tok, tok_spec)
            l_dev = self._put_device(lens, len_spec)
        # always-on device-traffic counters (obs/stages.py): the stream
        # regime's put count/bytes are gated numerically like the dedup
        # tile plane's
        stages.count_device_put(tok.nbytes, "feed")
        stages.count_device_put(lens.nbytes, "feed")
        self.timer.add(time.perf_counter() - t0, n)
        self._m_batches.inc()
        self._m_docs.inc(n)
        self._m_fill.set(n / self.batch_size)
        if n < self.batch_size:
            self._m_partial.inc()
        if trace.RECORDER.active:
            # the ingest end of the span chain: the first tag names the
            # batch, so a dump ties "what was staging" to the
            # kernel/resolve spans downstream
            trace.record(
                "span",
                "feed.stage",
                batch=int(tags[0]),
                rows=n,
                dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
            )
        return (n, t_dev, l_dev, tags)

    def __iter__(self) -> Iterator[tuple[int, object, object, np.ndarray]]:
        while True:
            item = self._out.pop()
            if item is DONE:
                # the closed edge makes termination idempotent — a caller
                # that catches the error (or re-iterates an exhausted
                # feed) terminates again instead of blocking forever
                if self._graph.error is not None:
                    raise RuntimeError(
                        "DeviceFeed worker died mid-stream"
                    ) from self._graph.error
                return
            yield item

    def join(self, timeout: float | None = 30.0) -> None:
        """Wait for every stage worker; ``timeout`` bounds the TOTAL wait."""
        self._graph.join(timeout, raise_error=False)


def stream_signatures(
    docs: Iterable[str | bytes],
    *,
    cfg: DedupConfig | None = None,
    block: int | None = None,
    batch_size: int | None = None,
    prefer_native: bool = True,
    sig_bits: int = 32,
    feed_workers: int | None = None,
) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Stream ``(tags, signatures, band_keys)`` batches for a document feed.

    A producer stage pushes ``docs`` through the native batcher while the
    main thread runs the device kernels on prefetched tiles — steady-state
    throughput is the device rate, not the Python iteration rate.

    ``sig_bits=16`` transfers only the low 16 bits of each signature lane
    (uint16) — lane-agreement still estimates Jaccard (collision noise
    2⁻¹⁶/lane) and the device→host volume halves, which matters on
    D2H-constrained links; band keys are always full uint32.

    ``feed_workers > 1`` overlaps device_put round trips on serializing
    transports (see :class:`DeviceFeed`); batches may then arrive out of
    submission order, which this path tolerates — tags ride with their
    batch and each batch's kernels are independent.  ``None``/0 = the
    transport default.
    """
    if sig_bits not in (16, 32):
        raise ValueError(f"sig_bits must be 16 or 32, got {sig_bits}")
    cfg = cfg or DedupConfig()
    block = block or cfg.block_len
    batch_size = batch_size or cfg.batch_size
    params = make_params(
        num_perm=cfg.num_perm,
        num_bands=cfg.num_bands,
        shingle_k=cfg.shingle_k,
        seed=cfg.seed,
    )
    salt = np.asarray(params.band_salt)

    batcher = HostBatcher(block, prefer_native=prefer_native)
    feed = DeviceFeed(batcher, batch_size, workers=feed_workers)

    # the producer pump is a one-stage graph of its own: feed() runs once
    # inside the source, the batcher close rides its finally, and a pump
    # death is visible on producer.error instead of vanishing with a thread
    def produce_once():
        try:
            batcher.feed(docs)
        finally:
            batcher.close()
        return DONE

    producer = StageGraph("stream_signatures")
    producer.stage("produce", source=produce_once)
    producer.start()

    import jax.numpy as jnp

    salt_j = jnp.asarray(salt)
    # One-deep result pipeline: batch i's D2H copy streams while batch i+1
    # computes (the D2H path is the narrow link on tunneled devices — see
    # .claude/skills/verify/SKILL.md).
    pending = None  # (tags, n, sig_dev, keys_dev)
    try:
        for n, tok_dev, len_dev, tags in feed:
            sig = minhash_signatures(tok_dev, len_dev, params)
            keys = band_keys(sig, salt_j)
            if sig_bits == 16:
                sig = (sig & jnp.uint32(0xFFFF)).astype(jnp.uint16)
            for arr in (sig, keys):
                try:
                    arr.copy_to_host_async()
                except AttributeError:
                    pass
            if pending is not None:
                ptags, pn, psig, pkeys = pending
                yield ptags[:pn], np.asarray(psig)[:pn], np.asarray(pkeys)[:pn]
            pending = (tags, n, sig, keys)
        if pending is not None:
            ptags, pn, psig, pkeys = pending
            yield ptags[:pn], np.asarray(psig)[:pn], np.asarray(pkeys)[:pn]
        # a dead pump means the stream above was silently TRUNCATED (the
        # closed batcher ends the feed cleanly) — the consumer must hear
        # about it, not discover a short corpus downstream
        producer.join(timeout=30, raise_error=False)
        if producer.error is not None:
            raise RuntimeError(
                "stream_signatures producer died mid-corpus"
            ) from producer.error
    finally:
        # on any exit — exhaustion, a dead feed worker, or the consumer
        # abandoning the generator — stop the producer promptly: a closed
        # batcher rejects further pushes, so feed() returns instead of
        # buffering the rest of `docs` into an undrained arena
        batcher.close()
        producer.join(timeout=30, raise_error=False)
        feed.join()
