"""Depth-N pipelined device-dispatch executor for the tile hot paths.

Every device-tile plane in the tree is the same four-stage pipeline —
**encode** (host rows → width-group tiles), **pack** (one contiguous
buffer per tile, ``ops/pack.py``), **put** (``jax.device_put``), and
**dispatch** (a fused jitted step) — and throughput on a
transfer-bound link comes from keeping all four saturated at once.
``pipeline/dedup.py`` used to hand-roll this twice (an inline loop at
``put_workers == 1``, a locked-generator stage graph above it); this
module is the ONE executor, expressed on the PR 7 runtime, and it is
deliberately workload-blind: the dedup signature plane
(``pipeline/dedup.py``, donated running accumulator) and the matcher
screen plane (``pipeline/matcher.py``, independent per-tile masks)
ride the same three stages, as does the legacy multi-array tile
transport kept alive for parity certification — ``pack``/``put`` are
caller-supplied callables, the executor knows nothing of either
workload:

- the ``pack`` stage draws tiles off the encode generator
  (``StageGraph``'s ``source_iter`` wraps it in a locked puller) and
  packs them on a worker thread, overlapping the next tile's encode
  with the previous tile's transfer;
- the ``h2d`` stage (``put_workers`` threads) issues the device puts —
  on transports where each put is a serialized round trip (DESIGN.md
  §5) concurrent puts overlap that latency;
- the caller's thread drains the ``staged`` edge and dispatches (the
  caller owns the dispatch because donation needs a single buffer
  owner — the dedup accumulator — and because matcher mask results
  must stay with the chunk's thread); the edge's capacity is the
  **dispatch window** (``ASTPU_DEDUP_DISPATCH_WINDOW`` /
  ``ASTPU_MATCH_DISPATCH_WINDOW``) — how many transferred tiles may
  wait in flight ahead of the dispatch.  Total resident tiles are
  bounded at ``window + put_workers + 1`` (buffered + transferring +
  dispatching) plus at most two packed host buffers awaiting transfer,
  so backpressure — not the encode rate — sets host memory.

Out-of-order arrival from the put pool never matters to either rider
(the dedup min-combine is order-independent; matcher tiles carry their
row→article owners); a worker error closes every edge and re-raises at
the consumer (the runtime's error fan-out).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator

from advanced_scrapper_tpu.runtime import DONE, StageGraph


def resolve_dispatch_window(window: int, put_workers: int) -> int:
    """Effective staged-edge capacity: explicit ``window`` wins, else
    ``max(2, put_workers)`` — double buffering on local backends, a
    put-pool-deep window on serializing transports (where puts complete
    out of order and a shallow edge would stall the pool)."""
    if window and window > 0:
        return window
    return max(2, put_workers)


class PipelinedDispatcher:
    """Run ``tiles → pack → put`` as a stage graph and iterate the staged
    results in the caller's thread, which owns the dispatch step (a
    donated accumulator must only ever be touched from one thread; a
    per-chunk mask drain must stay with its chunk).

    Iteration yields whatever ``put`` returned, ends when the encode
    iterator is exhausted and every staged tile was handed over, and
    re-raises the first worker error (with the original as cause).
    """

    def __init__(
        self,
        tiles: Iterable,
        *,
        pack: Callable,
        put: Callable,
        put_workers: int = 1,
        window: int = 0,
        name: str = "dedup.h2d",
    ):
        window = resolve_dispatch_window(window, put_workers)
        self._graph = StageGraph(name)
        # the packed edge is a FIXED two-deep buffer (pack is cheap next
        # to put+dispatch; two keeps the put pool fed across a pop) — it
        # must NOT scale with the window, or total resident tiles would
        # double past the documented window + put_workers + 1 bound
        packed = self._graph.edge("packed", capacity=2)
        self._staged = self._graph.edge("staged", capacity=window)
        self._graph.stage(
            "pack", source_iter=tiles, fn=pack, out_edge=packed
        )
        self._graph.stage(
            "h2d",
            in_edge=packed,
            fn=put,
            out_edge=self._staged,
            workers=max(1, put_workers),
        )
        self._graph.start()

    @property
    def error(self) -> BaseException | None:
        return self._graph.error

    def __iter__(self) -> Iterator:
        while True:
            item = self._staged.pop()
            if item is DONE:
                if self._graph.error is not None:
                    raise RuntimeError(
                        "pipelined dispatch worker died mid-corpus"
                    ) from self._graph.error
                return
            yield item

    def close(self, timeout: float = 30.0) -> None:
        """Stop the graph (idempotent; safe mid-iteration on error paths)."""
        self._graph.stop()
        self._graph.join(timeout=timeout, raise_error=False)
