"""Depth-N pipelined device-dispatch executor for the tile hot paths.

Every device-tile plane in the tree is the same four-stage pipeline —
**encode** (host rows → width-group tiles), **pack** (one contiguous
buffer per tile, ``ops/pack.py``), **put** (``jax.device_put``), and
**dispatch** (a fused jitted step) — and throughput on a
transfer-bound link comes from keeping all four saturated at once.
``pipeline/dedup.py`` used to hand-roll this twice (an inline loop at
``put_workers == 1``, a locked-generator stage graph above it); this
module is the ONE executor, expressed on the PR 7 runtime, and it is
deliberately workload-blind: the dedup signature plane
(``pipeline/dedup.py``, donated running accumulator), the matcher
screen plane (``pipeline/matcher.py``, independent per-tile masks) and
the MESH-SHARDED dedup plane (a sharded source on the same graph: each
"tile" is a per-shard group whose ``put`` issues one ``device_put`` per
shard and whose dispatch is one partitioned fused step —
``parallel/sharded_packed.py``) ride the same three stages, as does the
legacy multi-array tile transport kept alive for parity certification —
``pack``/``put`` are caller-supplied callables, the executor knows
nothing of any workload:

- the ``pack`` stage draws tiles off the encode generator
  (``StageGraph``'s ``source_iter`` wraps it in a locked puller) and
  packs them on a worker thread, overlapping the next tile's encode
  with the previous tile's transfer;
- the ``h2d`` stage (``put_workers`` threads) issues the device puts —
  on transports where each put is a serialized round trip (DESIGN.md
  §5) concurrent puts overlap that latency;
- the caller's thread drains the ``staged`` edge and dispatches (the
  caller owns the dispatch because donation needs a single buffer
  owner — the dedup accumulator — and because matcher mask results
  must stay with the chunk's thread); the edge's capacity is the
  **dispatch window** (``ASTPU_DEDUP_DISPATCH_WINDOW`` /
  ``ASTPU_MATCH_DISPATCH_WINDOW``) — how many transferred tiles may
  wait in flight ahead of the dispatch.  Total resident tiles are
  bounded at ``window + put_workers + 1`` (buffered + transferring +
  dispatching) plus at most two packed host buffers awaiting transfer,
  so backpressure — not the encode rate — sets host memory.

Every staged tile is stamped as the put pool finishes it and the gap to
the caller's pop lands on the always-on
``astpu_dispatch_queue_lag_seconds{graph}`` histogram (``obs/devprof.py``):
near-zero lag means the dispatch loop consumes tiles the moment they
land (dispatch is the bottleneck — deepen nothing), sustained lag means
H2D runs ahead and the window absorbs it (the transport is the
bottleneck — the knob sweeps have headroom).  The stamp is internal:
callers still iterate exactly what their ``put`` returned.

Out-of-order arrival from the put pool never matters to either rider
(the dedup min-combine is order-independent; matcher tiles carry their
row→article owners); a worker error closes every edge and re-raises at
the consumer (the runtime's error fan-out).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Iterable, Iterator

from advanced_scrapper_tpu.runtime import DONE, StageGraph

__all__ = [
    "DispatchTimeout",
    "OOM_FLOOR_ROWS",
    "PipelinedDispatcher",
    "dispatch_with_oom_backoff",
    "is_resource_exhausted",
    "resolve_dispatch_window",
    "resolve_watchdog_s",
]


class DispatchTimeout(RuntimeError):
    """The dispatch watchdog tripped: no tile made progress inside the
    wall-clock budget.  The graph is already torn down and the flight
    recorder already dumped when this reaches the caller — a counted,
    debuggable failure instead of a silent wedge."""


def resolve_watchdog_s(watchdog_s: float | None = None) -> float:
    """Effective per-tile watchdog budget: explicit value wins, else
    ``ASTPU_DISPATCH_WATCHDOG_S`` (seconds; 0 = off, the default — first
    tiles legitimately pay multi-second XLA compiles, so the budget is
    an operator's declaration, not a guess)."""
    if watchdog_s is not None and watchdog_s > 0:
        return float(watchdog_s)
    try:
        return float(os.environ.get("ASTPU_DISPATCH_WATCHDOG_S", "0") or 0)
    except ValueError:
        return 0.0


def resolve_dispatch_window(window: int, put_workers: int) -> int:
    """Effective staged-edge capacity: explicit ``window`` wins, else
    ``max(2, put_workers)`` — double buffering on local backends, a
    put-pool-deep window on serializing transports (where puts complete
    out of order and a shallow edge would stall the pool)."""
    if window and window > 0:
        return window
    return max(2, put_workers)


class PipelinedDispatcher:
    """Run ``tiles → pack → put`` as a stage graph and iterate the staged
    results in the caller's thread, which owns the dispatch step (a
    donated accumulator must only ever be touched from one thread; a
    per-chunk mask drain must stay with its chunk).

    Iteration yields whatever ``put`` returned, ends when the encode
    iterator is exhausted and every staged tile was handed over, and
    re-raises the first worker error (with the original as cause).
    """

    def __init__(
        self,
        tiles: Iterable,
        *,
        pack: Callable,
        put: Callable,
        put_workers: int = 1,
        window: int = 0,
        name: str = "dedup.h2d",
        watchdog_s: float | None = None,
    ):
        from advanced_scrapper_tpu.obs import devprof

        window = resolve_dispatch_window(window, put_workers)
        self._watchdog_s = resolve_watchdog_s(watchdog_s)
        self._beat = time.monotonic()
        self._finished = threading.Event()
        self._lag_hist = devprof.queue_lag_histogram(name)

        def stamped_put(item, _put=put):
            # the staged-pop lag clock starts the instant the transfer
            # completes (stamp taken AFTER _put returns — stamping first
            # would fold the whole H2D into "lag" and invert the
            # bottleneck diagnostic); __iter__ unwraps, so riders never
            # see the stamp
            staged = _put(item)
            return (time.perf_counter(), staged)

        self._graph = StageGraph(name)
        # the packed edge is a FIXED two-deep buffer (pack is cheap next
        # to put+dispatch; two keeps the put pool fed across a pop) — it
        # must NOT scale with the window, or total resident tiles would
        # double past the documented window + put_workers + 1 bound
        packed = self._graph.edge("packed", capacity=2)
        self._staged = self._graph.edge("staged", capacity=window)
        self._graph.stage(
            "pack", source_iter=tiles, fn=pack, out_edge=packed
        )
        self._graph.stage(
            "h2d",
            in_edge=packed,
            fn=stamped_put,
            out_edge=self._staged,
            workers=max(1, put_workers),
        )
        self._graph.start()
        if self._watchdog_s > 0:
            t = threading.Thread(
                target=self._watch, daemon=True, name=f"astpu-{name}-watchdog"
            )
            t.start()

    # -- watchdog ----------------------------------------------------------

    def beat(self) -> None:
        """Progress heartbeat.  The iterator beats on every staged pop
        and on every re-entry (i.e. after the caller's dispatch of the
        previous tile returned) — so a hang ANYWHERE on the tile path
        (encode, pack, put, or the caller's device dispatch) leaves the
        beat stale and trips the watchdog."""
        self._beat = time.monotonic()

    def _watch(self) -> None:
        budget = self._watchdog_s
        tick = max(0.01, min(0.25, budget / 4))
        while not self._finished.wait(tick):
            if time.monotonic() - self._beat <= budget:
                continue
            from advanced_scrapper_tpu.obs import telemetry, trace

            # counted timeout → flight-recorder dump (the fault hooks
            # land every live graph's drain snapshot in the ring first)
            # → whole-graph teardown.  The blocked consumer wakes on the
            # closed staged edge and re-raises; a consumer stuck INSIDE
            # a hung device call cannot be unwedged from here, but the
            # dump + teardown make the hang visible and bounded instead
            # of silent.
            telemetry.event_counter(
                "astpu_dispatch_watchdog_trips_total",
                "dispatch tiles that blew their wall-clock budget "
                "(graph torn down with a flight-recorder dump)",
            ).inc()
            trace.record(
                "event", "dispatch.watchdog",
                graph=self._graph.name, budget_s=budget,
            )
            trace.dump_on_fault(
                f"dispatch watchdog: no tile progress in {budget:.3g}s "
                f"on graph '{self._graph.name}'"
            )
            self._graph.fail(
                DispatchTimeout(
                    f"no tile progress in {budget:.3g}s "
                    f"(graph '{self._graph.name}')"
                )
            )
            return
        # clean finish: nothing to do

    @property
    def error(self) -> BaseException | None:
        return self._graph.error

    def __iter__(self) -> Iterator:
        while True:
            self.beat()  # re-entered: the caller's dispatch made progress
            item = self._staged.pop()
            self.beat()  # popped: the put pool made progress
            if item is DONE:
                self._finished.set()
                if self._graph.error is not None:
                    err = self._graph.error
                    if isinstance(err, DispatchTimeout):
                        raise err
                    raise RuntimeError(
                        "pipelined dispatch worker died mid-corpus"
                    ) from err
                return
            staged_ts, payload = item
            self._lag_hist.observe(time.perf_counter() - staged_ts)
            yield payload

    def close(self, timeout: float = 30.0) -> None:
        """Stop the graph (idempotent; safe mid-iteration on error paths)."""
        self._finished.set()
        self._graph.stop()
        self._graph.join(timeout=timeout, raise_error=False)


# -- device-OOM tile backoff --------------------------------------------------

#: halving floor: tiles never shrink below this row count (it is also the
#: chunker's minimum tail tile — ``core.tokenizer.tile_rows_options`` —
#: so every backoff shape is already in the prewarmed set and a backoff
#: ladder can never recompile-storm).  At the floor, a still-exhausted
#: device is a real capacity failure and the error propagates cleanly.
OOM_FLOOR_ROWS = 64

_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory")


def is_resource_exhausted(exc: BaseException) -> bool:
    """Does this exception smell like a device allocation failure?  XLA
    raises ``XlaRuntimeError`` with a ``RESOURCE_EXHAUSTED:`` status
    prefix; match on the message (the exception class moved modules
    across jaxlib versions, the status string never did)."""
    s = f"{type(exc).__name__}: {exc}".lower()
    return any(m in s for m in _OOM_MARKERS)


_chaos_oom_lock = threading.Lock()
_chaos_oom_used = 0


def reset_chaos_oom() -> None:
    """Re-arm the ``ASTPU_CHAOS_DISPATCH_OOM`` budget (tests)."""
    global _chaos_oom_used
    with _chaos_oom_lock:
        _chaos_oom_used = 0


def maybe_inject_oom(plane: str) -> None:
    """Chaos seam: ``ASTPU_CHAOS_DISPATCH_OOM=N`` makes the next N
    dispatch attempts raise a synthetic ``RESOURCE_EXHAUSTED`` (counted
    on the shared fault-injection ledger) — how tier-1 certifies the
    halving ladder on hardware that never actually OOMs."""
    spec = os.environ.get("ASTPU_CHAOS_DISPATCH_OOM", "")
    if not spec:
        return
    try:
        budget = int(spec)
    except ValueError:
        return
    if budget <= 0:
        return
    global _chaos_oom_used
    with _chaos_oom_lock:
        if _chaos_oom_used >= budget:
            return
        _chaos_oom_used += 1
    from advanced_scrapper_tpu.obs import telemetry

    telemetry.event_counter(
        "astpu_fault_injected_total",
        "chaos faults injected, by plane and kind",
        plane="dispatch", kind="oom",
    ).inc()
    raise RuntimeError(
        "RESOURCE_EXHAUSTED: injected device OOM (ASTPU_CHAOS_DISPATCH_OOM)"
    )


def dispatch_with_oom_backoff(
    fn: Callable,
    carry,
    item,
    *,
    split: Callable,
    rows_of: Callable,
    floor: int = OOM_FLOOR_ROWS,
    plane: str = "dedup",
):
    """Run one device dispatch ``fn(carry, item) -> carry`` with
    automatic tile-size backoff on device OOM.

    ``RESOURCE_EXHAUSTED`` (or the injected chaos equivalent) halves the
    tile: ``split(item)`` re-packs it as two half-row sub-tiles (paying
    one D2H + two H2D, all counted on the device ledger) and each half
    retries recursively — so a transient memory squeeze converges to the
    same fold, byte-identical, at smaller dispatch granularity.  Tiles
    are power-of-two rows, so every backoff shape is in the prewarmed
    O(log bs) set (no recompile storm).  At ``floor`` rows the error
    propagates — a clean, attributable failure, never a wedge.  Any
    non-OOM error propagates untouched.
    """
    try:
        maybe_inject_oom(plane)
        return fn(carry, item)
    except Exception as e:
        if not is_resource_exhausted(e):
            raise
        rows = int(rows_of(item))
        if rows <= floor or rows < 2:
            raise
        from advanced_scrapper_tpu.obs import telemetry, trace

        telemetry.event_counter(
            "astpu_dispatch_oom_backoff_total",
            "device-OOM tile halvings (re-packed and retried)",
            plane=plane,
        ).inc()
        trace.record(
            "event", "dispatch.oom_backoff", plane=plane,
            rows=rows, halved_to=rows // 2,
        )
        for sub in split(item):
            carry = dispatch_with_oom_backoff(
                fn, carry, sub,
                split=split, rows_of=rows_of, floor=floor, plane=plane,
            )
        return carry
