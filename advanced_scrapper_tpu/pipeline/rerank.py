"""Device-batched rerank (precision) tier on ``RERANK_HOOK_EDGE``.

:class:`RerankTier` is the engine's default ``rerank_hook``
(``DedupConfig.rerank``): it takes the candidate matrix the fused LSH
epilogue produced, settles every candidate pair's keep/kill verdict
with the vmap'd bottom-sketch Jaccard kernel (``ops/rerank.py``), and
returns a REWRITTEN candidate matrix that holds exactly the surviving
cluster edges — so both resolution paths (async estimator and the
certified one-shot) resolve the tier's verdicts instead of raw band
collisions.

Dataflow per corpus (the launch-count contract the tier-1 gate
asserts)::

    pairs   = coarse band buckets ∪ incoming candidate cells
    fold    = device_put(zeros[pair_cap])          # 1 put
    tiles   : pack_pair_tile → device_put → settle # 1 put + 1 dispatch
              (PipelinedDispatcher — encode/pack/put overlap, the
              caller's thread owns the donated fold)        × tiles
    finalize: fold → (jq, verdict)                 # 1 dispatch
    readback: ONCE                                 # Σ = tiles+1 / tiles+1

Verdicts inside the declared margin band (``rerank_margin``, ~3σ of
the sketch estimator) are re-settled on host: exact shingle Jaccard up
to ``rerank_exact_cap``, then — when a persistent index is attached —
an ANN re-probe over its segment postings (both docs' wide band keys,
``ops.rerank.band_keys_wide_host``; the pair survives when the index
attributes both to the same earliest posting).  Clusters formed from
the settled keep-edges then pass the precision-targeted eviction walk
(``ops.rerank.evict_for_precision``) with the recall floor as a hard
guard, and the surviving est-verified cluster edges are written back
as the new candidate matrix.

The tier is *authoritative*: verdicts already settled by true Jaccard
must not be second-guessed by the estimator-era exact-verify stage —
``NearDupEngine.dedup_reps`` detects ``authoritative = True`` and
resolves the rewritten matrix directly.  ``skip_rerank`` brownouts
bypass the hook in ``_prepare`` (counted, reversible) and restore the
hookless fused path byte-for-byte.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.ops import rerank as oprr
from advanced_scrapper_tpu.ops.pack import pack_pair_tile, pair_tile_nbytes

__all__ = ["RerankTier"]


class RerankTier:
    """Callable ``(raw, sigs, rep_bands, valid) → rep_bands`` for
    :data:`pipeline.dedup.RERANK_HOOK_EDGE` (see module docstring).

    ``index``: optional persistent index (``index.store.PersistentIndex``
    or a fleet client) for the borderline ANN re-probe; None (default)
    keeps the tier self-contained.  ``stats`` holds the last corpus's
    settlement ledger (tiles, bytes, borderline/exact/re-probe counts,
    evictions) for tests and the bench regime.
    """

    #: the certified path trusts the rewritten matrix as settled truth
    #: (see NearDupEngine.dedup_reps) — estimator-era exact-verify would
    #: refute deliberate keeps and drop settled recall
    authoritative = True

    def __init__(
        self,
        cfg: DedupConfig,
        params: MinHashParams,
        *,
        index=None,
    ):
        self.cfg = cfg
        self.params = params
        self.index = index
        self._steps: dict[int, object] = {}
        self._finalize_fn = None
        self.stats: dict = {}

    # -- compiled-step plumbing -------------------------------------------

    def _rows_options(self) -> list[int]:
        """The settle-tile shape set — the SAME derivation the engine
        tile planes prewarm through (``core.tokenizer.tile_rows_options``),
        so the recompile sentinel stays zero in steady state."""
        from advanced_scrapper_tpu.core.tokenizer import tile_rows_options

        return tile_rows_options(max(self.cfg.rerank_tile_rows, 64), 64)

    def _step(self, rows: int):
        step = self._steps.get(rows)
        if step is None:
            from advanced_scrapper_tpu.obs import devprof

            step = devprof.instrument_jit(
                oprr.make_rerank_tile_step(rows, self.cfg.rerank_sketch),
                "rerank_tile",
            )
            self._steps[rows] = step
        return step

    def _finalize(self):
        if self._finalize_fn is None:
            from advanced_scrapper_tpu.obs import devprof

            self._finalize_fn = devprof.instrument_jit(
                oprr.make_rerank_finalize(), "rerank_finalize"
            )
        return self._finalize_fn

    def _put_workers(self) -> int:
        if self.cfg.put_workers:
            return self.cfg.put_workers
        from advanced_scrapper_tpu.core.mesh import auto_h2d_workers

        return auto_h2d_workers()

    def prewarm(self) -> int:
        """Compile the full settle shape set (every ``_rows_options``
        tile plus the finalize) against zero buffers — after this, a
        real corpus leaves ``devprof.jit_compiles_total()`` flat.
        Returns the number of steps compiled."""
        import jax

        sketch = self.cfg.rerank_sketch
        cap = self.cfg.rerank_pair_cap
        fold = jax.device_put(np.zeros(cap, np.int32))
        compiled = 0
        for rows in self._rows_options():
            packed = pack_pair_tile(
                np.zeros((rows, sketch), np.uint32),
                np.zeros((rows, sketch), np.uint32),
                np.full(rows, cap, np.int32),  # OOB slots: scatter drops
            )
            fold = self._step(rows)(fold, jax.device_put(packed))
            compiled += 1
        jq, verdict = self._finalize()(fold, np.int32(0), np.int32(1))
        jax.block_until_ready(verdict)
        return compiled + 1

    # -- the tier ----------------------------------------------------------

    def _candidate_pairs(self, sigs, rb, valid, n):
        """Settlement work-list: datasketch-class coarse band pairs plus
        every incoming candidate cell (fine-band candidacy included),
        capped at the fold size with incoming cells prioritised."""
        pairs, capped = oprr.coarse_pairs(
            sigs[:n], valid[:n], self.params.num_bands
        )
        rows, cols = np.nonzero(rb != np.arange(rb.shape[0])[:, None])
        from_cells = set()
        for i, c in zip(rows, cols):
            j = int(rb[i, c])
            i = int(i)
            if i < n and j < n and valid[i] and valid[j] and i != j:
                from_cells.add((min(i, j), max(i, j)))
        extra = sorted(pairs - from_cells)
        ordered = sorted(from_cells) + extra
        cap = self.cfg.rerank_pair_cap
        overflow = max(0, len(ordered) - cap)
        return np.array(ordered[:cap], np.int64).reshape(-1, 2), {
            "capped_buckets": capped,
            "overflow_pairs": overflow,
        }

    def _settle_device(self, pair_arr, sketches):
        """Packed single-dispatch settlement: quantized Jaccard per pair
        slot, ONE readback.  Returns ``(jq int32[m], verdict int8[m],
        tiles, h2d_bytes)``."""
        import jax

        from advanced_scrapper_tpu.obs import stages
        from advanced_scrapper_tpu.pipeline.dispatch import (
            PipelinedDispatcher,
        )

        cfg = self.cfg
        sketch = cfg.rerank_sketch
        cap = cfg.rerank_pair_cap
        m = pair_arr.shape[0]
        thr = cfg.sim_threshold
        lo = np.int32(oprr.quantize(thr - cfg.rerank_margin))
        hi = np.int32(oprr.quantize(thr + cfg.rerank_margin))

        fold_init = np.zeros(cap, np.int32)
        fold = jax.device_put(fold_init)
        stages.count_device_put(fold_init.nbytes, "rerank")

        def tiles():
            # greedy power-of-two chunking over the shared shape set:
            # largest prewarmed tile that fits, smallest (zero-padded)
            # for the residue — same scheme as the encode chunkers
            off = 0
            options = sorted(self._rows_options(), reverse=True)
            while off < m:
                rem = m - off
                rows = next(
                    (o for o in options if o <= rem), options[-1]
                )
                take = min(rows, rem)
                yield rows, off, take
                off += take

        def pack(tile):
            rows, off, take = tile
            ii = pair_arr[off : off + take, 0]
            jj = pair_arr[off : off + take, 1]
            ska = np.zeros((rows, sketch), np.uint32)
            skb = np.zeros((rows, sketch), np.uint32)
            ska[:take] = sketches[ii]
            skb[:take] = sketches[jj]
            idx = np.full(rows, cap, np.int32)  # pad slots scatter-drop
            idx[:take] = np.arange(off, off + take, dtype=np.int32)
            return rows, pack_pair_tile(ska, skb, idx)

        def put(item):
            rows, packed = item
            dev = jax.device_put(packed)
            stages.count_device_put(packed.nbytes, "rerank")
            return rows, packed.nbytes, dev

        n_tiles = 0
        h2d = 0
        pipe = PipelinedDispatcher(
            tiles(),
            pack=pack,
            put=put,
            put_workers=self._put_workers(),
            window=cfg.dispatch_window,
            name="dedup.rerank.h2d",
        )
        try:
            for rows, nbytes, dev in pipe:
                fold = self._step(rows)(fold, dev)
                stages.count_dispatch("rerank")
                n_tiles += 1
                h2d += nbytes
        finally:
            pipe.close()
        jq_dev, verdict_dev = self._finalize()(fold, lo, hi)
        stages.count_dispatch("rerank")
        jq = np.asarray(jq_dev)[:m]  # the corpus's ONE readback
        verdict = np.asarray(verdict_dev)[:m]
        return jq, verdict, n_tiles, h2d

    def _reprobe(self, i: int, j: int, keys64) -> bool | None:
        """Borderline ANN re-probe over the persistent index's segment
        postings: both docs' wide band keys are probed; the pair survives
        when the index attributes both rows to the same earliest posted
        doc (their dup family already co-locates in the postings).
        None = no index attached / no evidence either way."""
        if self.index is None or keys64 is None:
            return None
        attr = np.asarray(self.index.probe_batch(keys64[[i, j]]))
        if attr[0] < 0 or attr[1] < 0:
            return None
        return bool(attr[0] == attr[1])

    def __call__(self, raw: Sequence[bytes], sigs, rep_bands, valid):
        from advanced_scrapper_tpu.cpu.oracle import jaccard, shingle_set
        from advanced_scrapper_tpu.utils.bloom import pack_keys64

        cfg = self.cfg
        thr = cfg.sim_threshold
        n = len(raw)
        sigs_np = np.asarray(sigs)
        rb = np.asarray(rep_bands)
        valid_np = np.asarray(valid)
        n_bucket, nc = rb.shape

        pair_arr, stats = self._candidate_pairs(sigs_np, rb, valid_np, n)
        m = pair_arr.shape[0]
        self.stats = stats
        stats.update(
            pairs=m, tiles=0, h2d_bytes=0, borderline=0,
            exact_checks=0, reprobes=0, evicted=0, clusters=0,
            dropped_cells=0, predicted_precision=1.0,
        )
        # decision provenance for the engine's emission pass: pairs the
        # HOST re-settled, keyed (lo, hi) → settling tier ("margin" exact
        # Jaccard / "reprobe" index ANN); everything else the device
        # sketch settled ("rerank", the consumer's default), and evicted
        # members' unique verdicts belong to the eviction walk
        prov: dict[tuple[int, int], str] = {}
        self.last_provenance = prov
        self.last_evicted: set[int] = set()
        self.last_participants: set[int] = set()
        if m == 0:
            out, _ = oprr.rewrite_rep_bands(n_bucket, nc, [])
            return out

        participating = np.zeros(n, bool)
        participating[np.unique(pair_arr)] = True
        self.last_participants = set(np.unique(pair_arr).tolist())
        sketches = oprr.bottom_sketches(
            raw, self.params.shingle_k, cfg.rerank_sketch,
            skip=~(participating & valid_np[:n]),
        )

        jq, verdict, n_tiles, h2d = self._settle_device(pair_arr, sketches)
        stats["tiles"] = n_tiles
        stats["h2d_bytes"] = h2d

        # host re-settle of the margin band: exact Jaccard up to the cap,
        # then the ANN re-probe, else the sketch verdict stands
        shingles: dict[int, set] = {}

        def sset(i: int) -> set:
            s = shingles.get(i)
            if s is None:
                s = shingles[i] = shingle_set(raw[i], self.params.shingle_k)
            return s

        exact_used = 0
        thr_q = oprr.quantize(thr)
        keep = verdict == 1
        border = np.flatnonzero(verdict == -1)
        stats["borderline"] = int(border.size)
        keys64 = None
        if self.index is not None and border.size:
            keys64 = pack_keys64(
                oprr.band_keys_wide_host(
                    sigs_np[:n], np.asarray(self.params.band_salt)
                )
            )

        def settle_exact(i: int, j: int, jq_ij: int) -> bool:
            nonlocal exact_used
            key = (i, j) if i < j else (j, i)
            if exact_used < cfg.rerank_exact_cap:
                exact_used += 1
                prov[key] = "margin"
                return jaccard(sset(i), sset(j)) >= thr
            rp = self._reprobe(i, j, keys64)
            if rp is not None:
                stats["reprobes"] += 1
                prov[key] = "reprobe"
                return rp
            prov[key] = "rerank"  # cap overflow: the sketch verdict stands
            return jq_ij >= thr_q

        for s in border:
            keep[s] = settle_exact(
                int(pair_arr[s, 0]), int(pair_arr[s, 1]), int(jq[s])
            )
        stats["exact_checks"] = exact_used

        # cluster the settled keep-edges, then classify EVERY
        # within-cluster pair (wave-2: residual pairs the candidacy never
        # proposed are settled on host — sketch twin, margin → exact)
        reps = oprr.union_find(n, pair_arr[keep])
        clusters: dict[int, list[int]] = {}
        for i in np.flatnonzero(valid_np[:n]):
            clusters.setdefault(int(reps[i]), []).append(int(i))
        clusters = {r: ms for r, ms in clusters.items() if len(ms) > 1}
        stats["clusters"] = len(clusters)

        settled = {
            (int(a), int(b)): (bool(k), int(q))
            for (a, b), k, q in zip(pair_arr, keep, jq)
        }
        margin = cfg.rerank_margin
        lanes = sigs_np.shape[1]
        # expected oracle-recall mass of the WHOLE candidate work-list —
        # candidacy is a superset of the estimator oracle's (coarse
        # buckets ⊆ candidates), so this prices the full recall
        # denominator, killed pairs included.  The eviction floor is
        # (live caught mass / this total): a number that maps directly
        # onto the measured-recall bar instead of an in-cluster ratio.
        total_op_mass = sum(
            oprr.op_weight(int(q) / oprr.SCALE, lanes, thr) for q in jq
        )
        pairinfo: dict[tuple[int, int], tuple[bool, float]] = {}
        for r, ms in clusters.items():
            for x in range(len(ms)):
                for y in range(x + 1, len(ms)):
                    a, b = ms[x], ms[y]
                    key = (a, b)
                    if key in settled:
                        is_keep, q = settled[key]
                        w = oprr.op_weight(q / oprr.SCALE, lanes, thr)
                    else:
                        jhat = oprr.sketch_jaccard(
                            sketches[a], sketches[b]
                        )
                        if abs(jhat - thr) < margin:
                            is_keep = settle_exact(
                                a, b, oprr.quantize(jhat)
                            )
                        else:
                            is_keep = jhat >= thr
                        # transitive extras the candidacy never proposed
                        # sit outside the estimator oracle's coarse
                        # buckets: merged or not, the recall denominator
                        # never counts them, so they carry zero mass —
                        # pure precision entries the eviction can drop
                        # for free
                        w = 0.0
                    pairinfo[key] = (not is_keep, w)
        stats["exact_checks"] = exact_used

        evicted, pprec = oprr.evict_for_precision(
            clusters,
            pairinfo,
            cfg.rerank_precision_target,
            recall_floor=cfg.rerank_recall_floor,
            total_op_mass=total_op_mass,
        )
        stats["evicted"] = len(evicted)
        stats["predicted_precision"] = pprec
        self.last_evicted = {int(d) for d in evicted}

        # surviving settled-TRUE cluster edges become the new candidate
        # matrix.  Truth, not the estimator: the engine's own lane
        # agreement is just another draw around the true J, and gating
        # edges on it re-drops exactly the proven-true pairs whose
        # signatures underestimate — the pairs the settle tier exists to
        # save.  Both resolve paths trust the authoritative rewrite
        # (``_rerank_applied``), so no downstream screen re-litigates.
        edges = []
        for r, ms in clusters.items():
            live = [d for d in ms if d not in evicted]
            for x in range(len(live)):
                for y in range(x + 1, len(live)):
                    a, b = live[x], live[y]
                    if not pairinfo[(a, b)][0]:
                        edges.append((a, b))
        out, dropped = oprr.rewrite_rep_bands(n_bucket, nc, edges)
        stats["dropped_cells"] = dropped
        return out
