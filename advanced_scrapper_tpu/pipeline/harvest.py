"""L1: Internet-Archive CDX URL harvest → deduplicated ``yfin_urls.csv``.

Re-implements ``yahoo_links_selenium.py`` semantics:

- shard space: every 2-character prefix over the reference's 39-char
  alphabet (a-z, 0-9, ``-``, ``_``, ``$``; ref ``:28``) → one CDX query per
  prefix (``:31-34``);
- **shard-file resume**: prefixes whose ``yahoo_<pfx>.txt`` already exists
  are skipped (``:29-33``) — the shard files ARE the checkpoint;
- per-shard parse of the space-delimited CDX dump with pandas (columns 1-2 →
  ``date_time,url``; ``:59``) and the exact normalisation chain
  (``:63-76``): keep rows containing ``.html`` (regex semantics preserved),
  truncate at ``.html``, strip ``:80``, ``http:``→``https:``, drop
  ``news/%`` and ``news/'`` junk; per-shard ``drop_duplicates`` (``:79``);
- **merge**: concat all shard CSVs and global exact-dedup keep-first.  This
  is the step the north star reroutes through the TPU backend: the 128-bit
  device hash proposes groups, the host confirms equality, and the output
  CSV is byte-identical to the pandas ``drop_duplicates`` path (``:174``)
  — asserted by golden tests.
"""

from __future__ import annotations

import glob
import io
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import pandas as pd
from bs4 import BeautifulSoup

from advanced_scrapper_tpu.config import HarvestConfig

CHAR_LIST = list("abcdefghijklmnopqrstuvwxyz") + list("1234567890") + ["-", "_", "$"]
# ref yahoo_links_selenium.py:28


def _atomic_write_df(path: str, df: pd.DataFrame, fs=None) -> None:
    """``df.to_csv`` streamed straight into the atomic tmp+fsync+rename
    commit — no whole-file string/bytes buffer (the merged url CSV can be
    hundreds of MB)."""
    from advanced_scrapper_tpu.storage.fsio import atomic_write

    def write(fh):
        wrapper = io.TextIOWrapper(fh, encoding="utf-8", newline="")
        try:
            df.to_csv(wrapper, index=False)
            wrapper.flush()
        finally:
            try:
                wrapper.detach()  # flush without closing the tmp handle
            except Exception:
                pass  # a failed write already owns the propagating error

    atomic_write(path, write, fs=fs)


def shard_prefixes(shard_dir: str) -> list[str]:
    """All 2-char prefixes without an existing shard file (resume, ref :29-34)."""
    done = set(os.listdir(shard_dir)) if os.path.isdir(shard_dir) else set()
    out = []
    for c0 in CHAR_LIST:
        for c1 in CHAR_LIST:
            if f"yahoo_{c0}{c1}.txt" not in done:
                out.append(c0 + c1)
    return out


def cdx_query_url(prefix: str, cfg: HarvestConfig) -> str:
    target = cfg.target_pattern.format(prefix=prefix)
    return f"{cfg.cdx_base}?url={target}"


def normalize_cdx_frame(df: pd.DataFrame) -> pd.DataFrame:
    """The reference's normalisation chain, verbatim semantics (ref :63-79).

    ``str.contains('.html')`` is kept with default regex=True on purpose —
    byte-identical output requires reproducing the reference's (technically
    sloppy) any-char dot.
    """
    df = df[df["url"].str.contains(".html")]
    df = df.copy()
    df["url"] = df["url"].str.split(".html").str[0] + ".html"
    df["url"] = df["url"].str.replace(":80", "", regex=False)
    df["url"] = df["url"].str.replace("http:", "https:", regex=False)
    df = df[~df["url"].str.contains("news/%")]
    df = df[~df["url"].str.contains("news/'")]
    df = df.drop_duplicates(subset=["url"])
    return df


def parse_cdx_text(text: str) -> pd.DataFrame:
    """Space-delimited CDX dump → (date_time, url) frame (ref :59)."""
    return pd.read_csv(
        io.StringIO(text),
        delimiter=" ",
        header=None,
        usecols=[1, 2],
        names=["date_time", "url"],
    )


def persist_shard(prefix: str, page: str, cfg: HarvestConfig, fs=None) -> str | None:
    """Parse + persist one fetched CDX shard page (ref :38-82) — the
    engine-independent half shared by the threaded and async harvesters,
    so their shard files are byte-identical by construction.

    Both files commit via ``fsio.atomic_replace`` (tmp+fsync+rename): a
    crash at any byte leaves each of them whole or absent, never torn.
    That matters doubly for the ``.txt``: it is the resume checkpoint
    ``shard_prefixes`` keys on, so a torn one would permanently mark an
    unfinished shard as done — the one failure the anti-join can't heal.
    """
    from advanced_scrapper_tpu.storage.fsio import atomic_replace

    text = BeautifulSoup(page, "html.parser").get_text(separator="\n", strip=True)
    csv_path = None
    if text.strip():
        df = normalize_cdx_frame(parse_cdx_text(text))
        csv_path = os.path.join(cfg.shard_dir, f"yahoo_{prefix}.csv")
        _atomic_write_df(csv_path, df, fs=fs)
    # the .txt is the resume checkpoint (shard_prefixes skips on it), so
    # it must be written only once the shard fully succeeded — the
    # reference writes it first (:52-54) and silently loses shards whose
    # parse then fails; checkpoint-last fixes that
    txt_path = os.path.join(cfg.shard_dir, f"yahoo_{prefix}.txt")
    atomic_replace(txt_path, text.encode("utf-8"), fs=fs)
    return csv_path


def process_shard(prefix: str, transport, cfg: HarvestConfig) -> str | None:
    """Fetch one CDX shard, persist raw text + normalised CSV (ref :38-82)."""
    url = cdx_query_url(prefix, cfg)
    try:
        return persist_shard(prefix, transport.fetch(url), cfg)
    except Exception as e:
        print(f"Error scraping {url}: {e}")
        return None


def merge_shards(cfg: HarvestConfig, *, use_tpu: bool = True) -> int:
    """Concat shard CSVs → global keep-first exact dedup → output CSV.

    ``use_tpu`` routes the dedup through ``pipeline.dedup.ExactDedup``
    (device hashing + host confirmation); the fallback is the reference's
    pandas path.  Outputs are byte-identical either way (golden-tested).
    """
    files = sorted(glob.glob(os.path.join(cfg.shard_dir, "*.csv")))
    dfs = []
    for f in files:
        try:
            dfs.append(pd.read_csv(f))
        except Exception as e:
            print(f"Error reading {f}: {e}")
    if not dfs:
        print("No CSV files were processed.")
        return 0
    merged = pd.concat(dfs, ignore_index=True)
    if use_tpu:
        from advanced_scrapper_tpu.pipeline.dedup import ExactDedup

        urls = merged["url"].astype(str).tolist()
        max_len = max((len(u.encode("utf-8", "replace")) for u in urls), default=1)
        keep = ExactDedup(max_len=max(4096, max_len)).keep_mask(urls)
        merged = merged[keep]
    else:
        merged = merged.drop_duplicates(subset=["url"])
    # atomic commit: a crash mid-merge must leave the previous output CSV
    # (which the scrape stage may already be consuming) whole, not torn
    _atomic_write_df(cfg.output_csv, merged)
    print(f"Found {len(merged)} unique URLs → {cfg.output_csv}")
    return len(merged)


def run_harvest(
    cfg: HarvestConfig,
    *,
    transport=None,
    transport_factory: Callable[[], object] | None = None,
    use_tpu: bool = True,
) -> int:
    """CLI entry: full shard sweep + merge (ref ``__main__`` :129-182)."""
    os.makedirs(cfg.shard_dir, exist_ok=True)
    prefixes = shard_prefixes(cfg.shard_dir)
    if prefixes:
        owns_transports = True  # workers close only transports they created
        if transport_factory is None:
            if transport is not None:
                shared = transport
                owns_transports = False  # caller-owned: never close it here
                transport_factory = lambda: shared  # noqa: E731
            else:
                from advanced_scrapper_tpu.net.transport import make_transport

                transport_factory = lambda: make_transport(  # noqa: E731
                    cfg.transport, ready_state_timeout=cfg.ready_state_timeout
                )
        print(f"Harvesting {len(prefixes)} CDX shards with {cfg.num_workers} workers")

        def worker_batch(batch: list[str]) -> None:
            t = transport_factory()
            try:
                for p in batch:
                    process_shard(p, t, cfg)
            finally:
                if owns_transports:
                    try:
                        t.close()
                    except Exception:
                        pass

        n = max(1, cfg.num_workers)
        batches = [prefixes[i::n] for i in range(n)]
        with ThreadPoolExecutor(max_workers=n) as ex:
            list(ex.map(worker_batch, [b for b in batches if b]))
    merge_shards(cfg, use_tpu=use_tpu)
    return 0
