// fastmatch: host-side exact verification kernels behind the TPU match screen.
//
// The reference leans on rapidfuzz (a C++ pip extension) for
// fuzz.partial_ratio (match_keywords.py:4,175-176).  This library provides
// the same semantics natively (dependency-free for deployment), with exact
// score parity CI-fuzzed against the installed rapidfuzz 3.x
// (tests/test_rapidfuzz_parity.py; `cpu/fuzz.py` is the pure-Python twin):
//
//   ratio(s1, s2)        = 100 * (1 - indel_dist / (|s1|+|s2|))
//                          with indel_dist = |s1|+|s2| - 2*LCS
//   partial_ratio(s1,s2) = max over sliding windows of the shorter string's
//                          length across the longer (including overhanging
//                          partial windows at both ends), with two
//                          rapidfuzz-3.x rules: an empty needle scores 0
//                          against non-empty text (100 only empty-vs-empty),
//                          and equal-length inputs are scanned in BOTH
//                          orientations (max taken) — see
//                          fuzz_py.partial_ratio_alignment in rapidfuzz.
//
// rapidfuzz scores UNICODE CODE POINTS, not bytes; the `_u32` entry points
// take UTF-32 sequences and match it exactly on non-ASCII text (curly
// quotes, accents, CJK).  The byte entry points remain for pure-ASCII
// fast paths and raw-bytes callers (identical results on ASCII).
//
// LCS length uses the Crochemore/Hyyrö bit-parallel recurrence
//   V = (V + (V & M)) | (V & ~M)
// over 64-bit words (multi-word with carry for patterns > 64 units);
// LCS = zero bits of V within the pattern length.  Complexity per call:
// O(windows * |window| * ceil(m/64)) — microseconds for typical entity
// names against full articles.
//
// Build: g++ -O3 -shared -fPIC fastmatch.cpp -o libfastmatch.so
// (driven automatically by cpu/native.py)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace {

// Pattern match-mask table over a 256-entry direct-indexed byte alphabet.
struct ByteMasks {
  int m;
  int words;
  std::vector<uint64_t> table;  // 256 x words

  explicit ByteMasks(const uint8_t* p, int len) : m(len), words((len + 63) / 64) {
    table.assign(256 * (size_t)words, 0);
    for (int i = 0; i < len; ++i) {
      table[(size_t)p[i] * words + (i >> 6)] |= 1ULL << (i & 63);
    }
  }

  const uint64_t* masks_for(uint8_t c) const { return &table[(size_t)c * words]; }
};

// Pattern match-mask table over the pattern's own (sorted, deduped)
// codepoint alphabet; haystack chars resolve by binary search, misses map
// to an all-zero mask.
struct CodepointMasks {
  int m;
  int words;
  std::vector<uint32_t> alpha;
  std::vector<uint64_t> table;  // alpha.size() x words
  std::vector<uint64_t> zero;   // words zeros

  explicit CodepointMasks(const uint32_t* p, int len)
      : m(len), words((len + 63) / 64) {
    alpha.assign(p, p + len);
    std::sort(alpha.begin(), alpha.end());
    alpha.erase(std::unique(alpha.begin(), alpha.end()), alpha.end());
    table.assign(alpha.size() * (size_t)words, 0);
    zero.assign(words, 0);
    for (int i = 0; i < len; ++i) {
      const size_t idx =
          std::lower_bound(alpha.begin(), alpha.end(), p[i]) - alpha.begin();
      table[idx * words + (i >> 6)] |= 1ULL << (i & 63);
    }
  }

  const uint64_t* masks_for(uint32_t c) const {
    auto it = std::lower_bound(alpha.begin(), alpha.end(), c);
    if (it == alpha.end() || *it != c) return zero.data();
    return &table[(size_t)(it - alpha.begin()) * words];
  }
};

// LCS length of the pattern (via masks) against text[0..tlen)
template <typename Masks, typename CharT>
int lcs_len(const Masks& pm, const CharT* text, int tlen) {
  const int words = pm.words;
  uint64_t vbuf[8];
  std::vector<uint64_t> vheap;
  uint64_t* V = vbuf;
  if (words > 8) {
    vheap.assign(words, ~0ULL);
    V = vheap.data();
  } else {
    for (int w = 0; w < words; ++w) vbuf[w] = ~0ULL;
  }
  for (int j = 0; j < tlen; ++j) {
    const uint64_t* M = pm.masks_for(text[j]);
    uint64_t carry = 0;
    for (int w = 0; w < words; ++w) {
      const uint64_t u = V[w] & M[w];
      const uint64_t sum = V[w] + u + carry;
      carry = (sum < V[w] || (carry && sum == V[w])) ? 1 : 0;
      V[w] = sum | (V[w] & ~M[w]);
    }
  }
  // LCS = zero bits within the first m positions
  int zeros = 0;
  for (int w = 0; w < words; ++w) {
    uint64_t mask = ~0ULL;
    const int remaining = pm.m - (w << 6);
    if (remaining < 64) mask = (remaining <= 0) ? 0 : ((1ULL << remaining) - 1);
    zeros += __builtin_popcountll(~V[w] & mask);
  }
  return zeros;
}

inline double indel_ratio(int m, int w, int lcs) {
  const int total = m + w;
  if (total == 0) return 100.0;
  return 200.0 * (double)lcs / (double)total;
}

// Max ratio of `needle` vs the length-m sliding windows of `haystack`
// (clipped at both edges).
template <typename Masks, typename CharT>
double scan_windows(const CharT* needle, int m, const CharT* haystack, int n) {
  Masks pm(needle, m);
  double best = 0.0;
  for (int start = -(m - 1); start < n; ++start) {
    const int lo = start > 0 ? start : 0;
    const int hi = (start + m) < n ? (start + m) : n;
    if (hi <= lo) continue;
    const int lcs = lcs_len(pm, haystack + lo, hi - lo);
    const double sc = indel_ratio(m, hi - lo, lcs);
    if (sc > best) {
      best = sc;
      if (best >= 100.0) break;
    }
  }
  return best;
}

// Sliding character-multiset intersection — an O(1)-per-position upper
// bound on the LCS of the needle vs each window (LCS ⊆ common multiset).
// Windows whose bound cannot reach `cutoff` skip the bit-parallel LCS
// entirely; with cutoff 95 and entity-name needles against article text,
// virtually every window is skipped, so the scan is O(n) counter updates
// plus rare exact rescores.  Exactness: a skipped window's true score ≤
// its bound < cutoff, and rapidfuzz score_cutoff semantics return 0 for
// results below cutoff anyway, so the returned value is identical to the
// full scan followed by thresholding (fuzzed in
// tests/test_rapidfuzz_parity.py).
//
// Counting alphabet: the byte path indexes a 256 table directly; the
// UTF-32 path maps haystack chars through the needle's sorted alphabet
// (misses contribute nothing — they can never be common).
struct ByteCounter {
  int counts[256];
  explicit ByteCounter(const uint8_t* p, int m) {
    std::memset(counts, 0, sizeof(counts));
    for (int i = 0; i < m; ++i) counts[p[i]]++;
  }
  static int index_of(const ByteCounter&, uint8_t c) { return c; }
  int size() const { return 256; }
};

struct CodepointCounter {
  std::vector<uint32_t> alpha;
  std::vector<int> counts;
  explicit CodepointCounter(const uint32_t* p, int m) {
    alpha.assign(p, p + m);
    std::sort(alpha.begin(), alpha.end());
    alpha.erase(std::unique(alpha.begin(), alpha.end()), alpha.end());
    counts.assign(alpha.size(), 0);
    for (int i = 0; i < m; ++i) {
      counts[std::lower_bound(alpha.begin(), alpha.end(), p[i]) -
             alpha.begin()]++;
    }
  }
  static int index_of(const CodepointCounter& nc, uint32_t c) {
    auto it = std::lower_bound(nc.alpha.begin(), nc.alpha.end(), c);
    if (it == nc.alpha.end() || *it != c) return -1;
    return (int)(it - nc.alpha.begin());
  }
  int size() const { return (int)alpha.size(); }
};

template <typename Masks, typename Counter, typename CharT>
double scan_windows_cutoff(const CharT* needle, int m, const CharT* haystack,
                           int n, double cutoff) {
  // Masks (the 2 KB bit-parallel table) builds lazily at the FIRST window
  // that survives the bound — the common all-pruned path pays only the
  // counter scan.  The counter's own needle alphabet is ≤ m entries, a
  // trivial build next to the masks table.
  std::unique_ptr<Masks> pm;
  const Counter nc(needle, m);
  std::vector<int> wcounts(nc.size(), 0);
  int inter = 0;  // Σ_c min(window_count[c], needle_count[c])
  auto add = [&](CharT ch) {
    const int idx = Counter::index_of(nc, ch);
    if (idx < 0) return;
    if (wcounts[idx] < nc.counts[idx]) ++inter;
    ++wcounts[idx];
  };
  auto del = [&](CharT ch) {
    const int idx = Counter::index_of(nc, ch);
    if (idx < 0) return;
    --wcounts[idx];
    if (wcounts[idx] < nc.counts[idx]) --inter;
  };
  double best = 0.0;
  int cur_lo = 0, cur_hi = 0;  // current counted window [cur_lo, cur_hi)
  for (int start = -(m - 1); start < n; ++start) {
    const int lo = start > 0 ? start : 0;
    const int hi = (start + m) < n ? (start + m) : n;
    if (hi <= lo) continue;
    while (cur_hi < hi) add(haystack[cur_hi++]);
    while (cur_lo < lo) del(haystack[cur_lo++]);
    const double ub = indel_ratio(m, hi - lo, inter);
    if (ub < cutoff || ub <= best) continue;  // cannot reach cutoff / improve
    if (!pm) pm.reset(new Masks(needle, m));
    const int lcs = lcs_len(*pm, haystack + lo, hi - lo);
    const double sc = indel_ratio(m, hi - lo, lcs);
    if (sc > best) {
      best = sc;
      if (best >= 100.0) break;
    }
  }
  return best >= cutoff ? best : 0.0;
}

template <typename Masks, typename Counter, typename CharT>
double partial_ratio_cutoff_impl(const CharT* s1, int len1, const CharT* s2,
                                 int len2, double cutoff) {
  const CharT* shorter = s1;
  const CharT* longer = s2;
  int m = len1, n = len2;
  if (len1 > len2) {
    shorter = s2; longer = s1; m = len2; n = len1;
  }
  if (m == 0) {
    const double sc = (n == 0) ? 100.0 : 0.0;
    return sc >= cutoff ? sc : 0.0;
  }
  double best = scan_windows_cutoff<Masks, Counter>(shorter, m, longer, n, cutoff);
  if (best < 100.0 && m == n) {
    const double rev =
        scan_windows_cutoff<Masks, Counter>(longer, n, shorter, m, cutoff);
    if (rev > best) best = rev;
  }
  return best;
}

template <typename Masks, typename CharT>
double ratio_impl(const CharT* s1, int len1, const CharT* s2, int len2) {
  if (len1 + len2 == 0) return 100.0;
  if (len1 == 0 || len2 == 0) return 0.0;
  Masks pm(s1, len1);
  const int lcs = lcs_len(pm, s2, len2);
  return indel_ratio(len1, len2, lcs);
}

// rapidfuzz 3.x partial_ratio semantics (see header comment).
template <typename Masks, typename CharT>
double partial_ratio_impl(const CharT* s1, int len1, const CharT* s2, int len2) {
  const CharT* shorter = s1;
  const CharT* longer = s2;
  int m = len1, n = len2;
  if (len1 > len2) {
    shorter = s2; longer = s1; m = len2; n = len1;
  }
  if (m == 0) return n == 0 ? 100.0 : 0.0;
  double best = scan_windows<Masks>(shorter, m, longer, n);
  if (best < 100.0 && m == n) {
    // equal lengths: rapidfuzz scans both orientations and takes the max
    const double rev = scan_windows<Masks>(longer, n, shorter, m);
    if (rev > best) best = rev;
  }
  return best;
}

}  // namespace

extern "C" {

// Normalised indel similarity in [0, 100] over bytes.
double fm_ratio(const uint8_t* s1, int len1, const uint8_t* s2, int len2) {
  return ratio_impl<ByteMasks>(s1, len1, s2, len2);
}

// Normalised indel similarity over UTF-32 code points (lengths in units).
double fm_ratio_u32(const uint32_t* s1, int len1, const uint32_t* s2, int len2) {
  return ratio_impl<CodepointMasks>(s1, len1, s2, len2);
}

// partial_ratio over bytes (exact rapidfuzz parity for pure-ASCII input).
double fm_partial_ratio(const uint8_t* s1, int len1, const uint8_t* s2, int len2) {
  return partial_ratio_impl<ByteMasks>(s1, len1, s2, len2);
}

// partial_ratio over UTF-32 code points — exact rapidfuzz parity on any text.
double fm_partial_ratio_u32(
    const uint32_t* s1, int len1, const uint32_t* s2, int len2) {
  return partial_ratio_impl<CodepointMasks>(s1, len1, s2, len2);
}

// partial_ratio with rapidfuzz score_cutoff semantics: exact score when it
// reaches `cutoff`, else 0.0.  The multiset upper bound skips nearly every
// window at high cutoffs (the matcher's >95 verify), ~10-50× the full scan.
double fm_partial_ratio_cutoff(const uint8_t* s1, int len1, const uint8_t* s2,
                               int len2, double cutoff) {
  return partial_ratio_cutoff_impl<ByteMasks, ByteCounter>(
      s1, len1, s2, len2, cutoff);
}

double fm_partial_ratio_cutoff_u32(const uint32_t* s1, int len1,
                                   const uint32_t* s2, int len2,
                                   double cutoff) {
  return partial_ratio_cutoff_impl<CodepointMasks, CodepointCounter>(
      s1, len1, s2, len2, cutoff);
}

// Batch: one needle against many haystacks (offsets into a byte arena).
// Scores must point at n doubles.
void fm_partial_ratio_batch(
    const uint8_t* needle, int needle_len,
    const uint8_t* arena, const int64_t* offsets, const int32_t* lengths,
    int n, double* scores) {
  for (int i = 0; i < n; ++i) {
    scores[i] = fm_partial_ratio(needle, needle_len, arena + offsets[i], lengths[i]);
  }
}

// Batch with score_cutoff: ONE haystack (an article/title) against a
// PERSISTENT packed needle arena (entity names, built once per index) with
// a per-call int32 row selection — the matcher's verify shape.  One call
// replaces a ctypes round trip (plus a fresh haystack encode) per name;
// each pair scores exactly like fm_partial_ratio_cutoff (the impl's
// shorter/longer swap makes argument order irrelevant).  scores[i]
// corresponds to select[i] and must point at n_select doubles.
void fm_partial_ratio_cutoff_select(
    const uint8_t* hay, int hay_len,
    const uint8_t* arena, const int64_t* offsets, const int32_t* lengths,
    const int32_t* select, int n_select, double cutoff, double* scores) {
  for (int i = 0; i < n_select; ++i) {
    const int r = select[i];
    scores[i] = fm_partial_ratio_cutoff(arena + offsets[r], lengths[r],
                                        hay, hay_len, cutoff);
  }
}

}  // extern "C"

// -- multi-pattern matcher core (Aho-Corasick over bytes) --------------------
//
// One automaton scan finds EVERY occurrence of EVERY pattern in a single
// pass over the text — the host-side successor of the matcher's per-name
// `re.finditer` loops (match_keywords.py:165-173 reroute), where each
// ALL-CAPS entity name used to re-scan the whole article.  Word-boundary
// (\b) filtering and per-name non-overlap stay on the Python side, where
// the regex semantics live; this core only enumerates raw (pattern, start)
// hits.  Classic goto/fail/output construction over the byte alphabet with
// sparse per-node edges (entity sets are small; scan cost is a couple of
// array/loop steps per text byte).

namespace {

struct AcNode {
  // sorted sparse edges: byte -> node index
  std::vector<std::pair<uint8_t, int32_t>> next;
  int32_t fail = 0;
  int32_t out_link = -1;   // nearest suffix node that ends a pattern
  int32_t pattern = -1;    // pattern id ending here (-1 = none)

  int32_t find(uint8_t c) const {
    for (const auto& e : next)
      if (e.first == c) return e.second;
    return -1;
  }
};

struct AcAutomaton {
  std::vector<AcNode> nodes;
  std::vector<int32_t> pat_len;
};

}  // namespace

extern "C" {

// Build an automaton over n patterns (pattern i = blob[offsets[i],
// offsets[i+1])).  Empty patterns are skipped (they can never match).
void* fm_ac_build(const uint8_t* blob, const int64_t* offsets, long n) {
  auto* ac = new (std::nothrow) AcAutomaton();
  if (!ac) return nullptr;
  ac->nodes.emplace_back();  // root
  ac->pat_len.assign(n, 0);
  for (long i = 0; i < n; ++i) {
    const int64_t len = offsets[i + 1] - offsets[i];
    ac->pat_len[i] = static_cast<int32_t>(len);
    if (len <= 0) continue;
    int32_t cur = 0;
    for (int64_t k = 0; k < len; ++k) {
      const uint8_t c = blob[offsets[i] + k];
      int32_t nxt = ac->nodes[cur].find(c);
      if (nxt < 0) {
        nxt = static_cast<int32_t>(ac->nodes.size());
        ac->nodes.emplace_back();
        ac->nodes[cur].next.emplace_back(c, nxt);
      }
      cur = nxt;
    }
    if (ac->nodes[cur].pattern < 0) ac->nodes[cur].pattern =
        static_cast<int32_t>(i);
    // duplicate pattern strings: first id wins; Python dedups names first
  }
  // BFS fail links
  std::vector<int32_t> queue;
  for (const auto& e : ac->nodes[0].next) {
    ac->nodes[e.second].fail = 0;
    queue.push_back(e.second);
  }
  for (size_t qi = 0; qi < queue.size(); ++qi) {
    const int32_t u = queue[qi];
    for (const auto& e : ac->nodes[u].next) {
      const uint8_t c = e.first;
      const int32_t v = e.second;
      int32_t f = ac->nodes[u].fail;
      int32_t t;
      while ((t = ac->nodes[f].find(c)) < 0 && f != 0) f = ac->nodes[f].fail;
      ac->nodes[v].fail = t >= 0 && t != v ? t : 0;
      const int32_t fv = ac->nodes[v].fail;
      ac->nodes[v].out_link =
          ac->nodes[fv].pattern >= 0 ? fv : ac->nodes[fv].out_link;
      queue.push_back(v);
    }
  }
  return ac;
}

void fm_ac_destroy(void* h) { delete static_cast<AcAutomaton*>(h); }

// Scan text, emitting (pattern id, start offset) for every occurrence of
// every pattern.  Returns the TOTAL number of occurrences; only the first
// `cap` are written to out_ids/out_starts (callers grow and re-scan when
// the return value exceeds cap).  Hits are emitted in end-position order,
// so per-pattern start offsets arrive ascending — what the finditer
// non-overlap replay on the Python side needs.
long fm_ac_scan(void* h, const uint8_t* text, long len, int32_t* out_ids,
                int64_t* out_starts, long cap) {
  const auto* ac = static_cast<const AcAutomaton*>(h);
  long hits = 0;
  int32_t cur = 0;
  for (long pos = 0; pos < len; ++pos) {
    const uint8_t c = text[pos];
    int32_t t;
    while ((t = ac->nodes[cur].find(c)) < 0 && cur != 0)
      cur = ac->nodes[cur].fail;
    cur = t >= 0 ? t : 0;
    for (int32_t o = cur; o >= 0; o = ac->nodes[o].out_link) {
      const int32_t pid = ac->nodes[o].pattern;
      if (pid >= 0) {
        if (hits < cap) {
          out_ids[hits] = pid;
          out_starts[hits] = pos + 1 - ac->pat_len[pid];
        }
        hits++;
      }
    }
  }
  return hits;
}

}  // extern "C"
