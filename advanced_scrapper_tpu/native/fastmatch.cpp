// fastmatch: host-side exact verification kernels behind the TPU match screen.
//
// The reference leans on rapidfuzz (a C++ pip extension) for
// fuzz.partial_ratio (match_keywords.py:4,175-176).  rapidfuzz is not
// available in this environment, so this library provides the same
// semantics natively (and `cpu/fuzz.py` is the pure-Python oracle it is
// tested against):
//
//   ratio(s1, s2)        = 100 * (1 - indel_dist / (|s1|+|s2|))
//                          with indel_dist = |s1|+|s2| - 2*LCS
//   partial_ratio(s1,s2) = max over sliding windows of the shorter string's
//                          length across the longer (including overhanging
//                          partial windows at both ends)
//
// LCS length uses the Crochemore/Hyyrö bit-parallel recurrence
//   V = (V + (V & M)) | (V & ~M)
// over 64-bit words (multi-word with carry for patterns > 64 bytes);
// LCS = zero bits of V within the pattern length.  Complexity per call:
// O(windows * |window| * ceil(m/64)) — microseconds for typical entity
// names against full articles.
//
// Build: g++ -O3 -shared -fPIC fastmatch.cpp -o libfastmatch.so
// (driven automatically by cpu/native.py)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct PatternMasks {
  int m;
  int words;
  // 256 characters x words bitmask table
  std::vector<uint64_t> table;

  explicit PatternMasks(const uint8_t* p, int len) : m(len), words((len + 63) / 64) {
    table.assign(256 * (size_t)words, 0);
    for (int i = 0; i < len; ++i) {
      table[(size_t)p[i] * words + (i >> 6)] |= 1ULL << (i & 63);
    }
  }
};

// LCS length of the pattern (via masks) against text[0..tlen)
int lcs_len(const PatternMasks& pm, const uint8_t* text, int tlen) {
  const int words = pm.words;
  uint64_t vbuf[8];
  std::vector<uint64_t> vheap;
  uint64_t* V = vbuf;
  if (words > 8) {
    vheap.assign(words, ~0ULL);
    V = vheap.data();
  } else {
    for (int w = 0; w < words; ++w) vbuf[w] = ~0ULL;
  }
  for (int j = 0; j < tlen; ++j) {
    const uint64_t* M = &pm.table[(size_t)text[j] * words];
    uint64_t carry = 0;
    for (int w = 0; w < words; ++w) {
      const uint64_t u = V[w] & M[w];
      const uint64_t sum = V[w] + u + carry;
      carry = (sum < V[w] || (carry && sum == V[w])) ? 1 : 0;
      V[w] = sum | (V[w] & ~M[w]);
    }
  }
  // LCS = zero bits within the first m positions
  int zeros = 0;
  for (int w = 0; w < words; ++w) {
    uint64_t mask = ~0ULL;
    const int remaining = pm.m - (w << 6);
    if (remaining < 64) mask = (remaining <= 0) ? 0 : ((1ULL << remaining) - 1);
    zeros += __builtin_popcountll(~V[w] & mask);
  }
  return zeros;
}

inline double indel_ratio(int m, int w, int lcs) {
  const int total = m + w;
  if (total == 0) return 100.0;
  return 200.0 * (double)lcs / (double)total;
}

}  // namespace

extern "C" {

// Normalised indel similarity in [0, 100].
double fm_ratio(const uint8_t* s1, int len1, const uint8_t* s2, int len2) {
  if (len1 + len2 == 0) return 100.0;
  if (len1 == 0 || len2 == 0) return 0.0;
  PatternMasks pm(s1, len1);
  const int lcs = lcs_len(pm, s2, len2);
  return indel_ratio(len1, len2, lcs);
}

// Sliding-window partial ratio (rapidfuzz semantics; see header comment).
double fm_partial_ratio(const uint8_t* s1, int len1, const uint8_t* s2, int len2) {
  const uint8_t* shorter = s1;
  const uint8_t* longer = s2;
  int m = len1, n = len2;
  if (len1 > len2) {
    shorter = s2; longer = s1; m = len2; n = len1;
  }
  if (m == 0) return 100.0;
  PatternMasks pm(shorter, m);
  double best = 0.0;
  for (int start = -(m - 1); start < n; ++start) {
    const int lo = start > 0 ? start : 0;
    const int hi = (start + m) < n ? (start + m) : n;
    if (hi <= lo) continue;
    const int lcs = lcs_len(pm, longer + lo, hi - lo);
    const double sc = indel_ratio(m, hi - lo, lcs);
    if (sc > best) {
      best = sc;
      if (best >= 100.0) break;
    }
  }
  return best;
}

// Batch: one needle against many haystacks (offsets into a byte arena).
// Scores must point at n doubles.
void fm_partial_ratio_batch(
    const uint8_t* needle, int needle_len,
    const uint8_t* arena, const int64_t* offsets, const int32_t* lengths,
    int n, double* scores) {
  for (int i = 0; i < n; ++i) {
    scores[i] = fm_partial_ratio(needle, needle_len, arena + offsets[i], lengths[i]);
  }
}

}  // extern "C"
