// Shared 64-bit byte-string hash (wyhash-flavoured multiply-mix over
// 8-byte words) for the exact-dedup tiers (hostbatch.cpp blob pass,
// exactdedup.cpp zero-copy pass).  ONE definition so the two tiers can
// never drift: equality decisions are always settled by memcmp, so hash
// quality only affects probe-chain length — but both tiers must still
// agree about what "the hash" is when results are compared side by side.
#pragma once

#include <cstdint>
#include <cstring>
#include <new>

namespace bytehash {

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 32;
  x *= 0xD6E8FEB86659FD93ULL;
  x ^= x >> 32;
  x *= 0xD6E8FEB86659FD93ULL;
  x ^= x >> 32;
  return x;
}

inline uint64_t hash_bytes(const uint8_t* p, uint64_t len) {
  uint64_t h = 0x9E3779B97F4A7C15ULL ^ len;
  uint64_t i = 0;
  for (; i + 8 <= len; i += 8) {
    uint64_t w;
    std::memcpy(&w, p + i, 8);
    h = mix64(h ^ w) * 0x9E3779B97F4A7C15ULL;
  }
  uint64_t tail = 0;
  if (i < len) {
    std::memcpy(&tail, p + i, len - i);
    h = mix64(h ^ tail) * 0x9E3779B97F4A7C15ULL;
  }
  return mix64(h);
}

// Shared open-addressing first-seen membership pass for the exact-dedup
// tiers.  ptr_of(i)/len_of(i) view item i's bytes (zero-copy in the list
// tier, blob+offsets in the portable tier); out_keep[i] = 1 iff item i is
// the first occurrence of its byte string.  Every hash-equal probe is
// settled by full memcmp — a collision lengthens a probe chain, never
// drops a distinct row.  Returns items kept, or -1 on allocation failure.
// ONE implementation so the tiers' probe/confirm semantics cannot drift.
template <typename PtrFn, typename LenFn>
long keep_first(long n, PtrFn ptr_of, LenFn len_of, uint8_t* out_keep) {
  if (n < 0) return -1;
  if (n == 0) return 0;
  struct Slot {
    uint64_t hash;
    int64_t idx;
  };
  // power-of-two table at >= 2n (load factor <= 0.5); hash and index
  // interleave so a probe costs one cache line, not two
  size_t cap = 16;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  void* raw = nullptr;
  {
    // no std::vector here: this header serves a translation unit compiled
    // against Python.h; keep the dependency surface minimal
    raw = ::operator new[](cap * sizeof(Slot), std::nothrow);
    if (!raw) return -1;
  }
  Slot* table = static_cast<Slot*>(raw);
  for (size_t s = 0; s < cap; ++s) table[s] = Slot{0, -1};
  const size_t mask = cap - 1;
  long kept = 0;
  for (long i = 0; i < n; ++i) {
    const uint8_t* item = ptr_of(i);
    const int64_t len = len_of(i);
    if (len < 0) {
      ::operator delete[](raw);
      return -1;
    }
    const uint64_t h = hash_bytes(item, static_cast<uint64_t>(len));
    size_t pos = static_cast<size_t>(h) & mask;
    int keep = 1;
    while (table[pos].idx != -1) {
      if (table[pos].hash == h) {
        const int64_t j = table[pos].idx;
        if (len_of(j) == len &&
            std::memcmp(ptr_of(j), item, static_cast<size_t>(len)) == 0) {
          keep = 0;  // true duplicate of an earlier item
          break;
        }
      }
      pos = (pos + 1) & mask;  // collision (hash or table slot): probe on
    }
    if (keep) {
      table[pos] = Slot{h, i};
      kept++;
    }
    out_keep[i] = static_cast<uint8_t>(keep);
  }
  ::operator delete[](raw);
  return kept;
}

}  // namespace bytehash
