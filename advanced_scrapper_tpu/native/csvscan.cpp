// Streaming RFC4180 CSV column scanner.
//
// The framework's resume anti-join re-reads the success/failed CSVs on
// every start (the reference does the same with pandas' C parser,
// constant_rate_scrapper.py:316-356).  Those files carry full article
// bodies — multi-GB after a long crawl — and the values contain commas,
// quotes and newlines, so a correct quoted-field state machine is
// required; a line-split fast path would mis-parse them.
//
// One pass, fixed 1 MiB read buffer, materialises only the header row and
// the target column's values.  Output: a malloc'd arena of NUL-terminated
// values back to back (count entries), freed by the caller via csv_free.
//
// Semantics mirror Python csv.DictReader on the default dialect:
//   - quoted fields may contain delimiters, CR/LF, and doubled quotes;
//   - completely blank rows are skipped;
//   - rows shorter than the header contribute no value for the column;
//   - rows longer than the header ignore the extras.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Scanner {
    std::string field;            // current field (materialised when needed)
    std::vector<std::string> header;
    std::string out;              // value arena
    long long count = 0;
    int col = 0;                  // current column index in this row
    int target = -1;              // resolved target column index
    bool in_quotes = false;
    bool after_quote = false;     // just saw a quote inside a quoted field
    bool at_field_start = true;   // no char consumed yet in this field
    bool row_started = false;     // any char or delimiter seen this row
    bool header_done = false;
    const char* want = nullptr;

    bool materialise() const {
        return !header_done || (target >= 0 && col == target);
    }

    void end_field() {
        if (!header_done) {
            header.push_back(field);
        } else if (target >= 0 && col == target) {
            out.append(field);
            out.push_back('\0');
            ++count;
        }
        field.clear();
        ++col;
        in_quotes = false;
        after_quote = false;
        at_field_start = true;
    }

    // returns false when the target column is absent from the header
    bool end_row() {
        if (!row_started) return true;  // blank line: skip (DictReader parity)
        end_field();
        if (!header_done) {
            header_done = true;
            // keep the LAST matching column: csv.DictReader's dict build
            // overwrites duplicates, so the last duplicate's values win —
            // the Python fallback and this scanner must agree
            for (size_t i = 0; i < header.size(); ++i) {
                if (want && header[i] == want) target = (int)i;
            }
            if (target < 0) return false;
        }
        col = 0;
        row_started = false;
        return true;
    }

    bool feed(const char* buf, size_t n) {
        for (size_t i = 0; i < n; ++i) {
            char c = buf[i];
            if (in_quotes) {
                if (after_quote) {
                    after_quote = false;
                    if (c == '"') { if (materialise()) field.push_back('"'); continue; }
                    in_quotes = false;
                    // fall through: c is an ordinary structural char now
                } else if (c == '"') {
                    after_quote = true;
                    continue;
                } else {
                    if (materialise()) field.push_back(c);
                    continue;
                }
            }
            // an opening quote only at field start; field.empty() would
            // misfire for non-materialised columns, whose buffer stays empty
            if (c == '"' && at_field_start) {
                in_quotes = true; row_started = true; at_field_start = false;
                continue;
            }
            if (c == ',') { row_started = true; end_field(); continue; }
            if (c == '\n') { if (!end_row()) return false; continue; }
            if (c == '\r') continue;  // CRLF / stray CR outside quotes
            row_started = true;
            at_field_start = false;
            if (materialise()) field.push_back(c);
        }
        return true;
    }

    bool finish() {
        if (in_quotes && after_quote) { in_quotes = false; after_quote = false; }
        if (row_started) return end_row();
        return true;
    }
};

}  // namespace

extern "C" {

char* csv_scan_column(const char* path, const char* column,
                      long long* count, long long* nbytes) {
    *count = 0;
    *nbytes = 0;
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    Scanner s;
    s.want = column;
    std::vector<char> buf(1 << 20);
    bool ok = true;
    size_t n;
    while (ok && (n = std::fread(buf.data(), 1, buf.size(), f)) > 0) {
        ok = s.feed(buf.data(), n);
    }
    std::fclose(f);
    if (!ok || !s.finish()) return nullptr;
    char* arena = (char*)std::malloc(s.out.size() ? s.out.size() : 1);
    if (!arena) return nullptr;
    std::memcpy(arena, s.out.data(), s.out.size());
    *count = s.count;
    *nbytes = (long long)s.out.size();
    return arena;
}

void csv_free(char* p) { std::free(p); }

}  // extern "C"
