// Host-side feed queue + fixed-shape batch assembler.
//
// The TPU-era successor of the reference's url_queue/result_queue plumbing
// (constant_rate_scrapper.py:146,437-469) and the C++ "host queue + batcher"
// SURVEY.md §7.3 mandates: producers (fetch/extract threads) push
// variable-length byte documents; the consumer pops fixed-shape
// uint8[batch, block] tiles with lengths + caller tags, zero-padded, ready
// for jax.device_put.  Batch assembly is memset+memcpy here so the Python
// feed thread does no per-document work at pop time.
//
// Concurrency: MPMC under one mutex (the critical sections are memcpys of
// ~1 KB documents — far from contended at the 50k docs/s north star);
// condvar wakeups for blocking pops; a byte-arena cap bounds host memory and
// gives natural backpressure (push returns 0; callers decide to block/drop).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

#include "bytehash.h"

namespace {

struct Doc {
  std::vector<uint8_t> bytes;
  uint64_t tag;
};

struct HostBatch {
  std::mutex mu;
  std::condition_variable not_empty;
  std::deque<Doc> q;
  size_t max_docs;
  size_t arena_cap;
  size_t arena_used = 0;
  uint64_t pushed = 0;
  uint64_t popped = 0;
  uint64_t rejected = 0;
  bool closed = false;
};

}  // namespace

extern "C" {

void* hb_create(long max_docs, long arena_bytes) {
  auto* h = new HostBatch();
  h->max_docs = max_docs > 0 ? static_cast<size_t>(max_docs) : SIZE_MAX;
  h->arena_cap = arena_bytes > 0 ? static_cast<size_t>(arena_bytes) : SIZE_MAX;
  return h;
}

// 1 = accepted; 0 = queue full (backpressure) or closed.
int hb_push(void* hp, const uint8_t* data, long len, uint64_t tag) {
  auto* h = static_cast<HostBatch*>(hp);
  if (len < 0) return 0;
  std::lock_guard<std::mutex> lk(h->mu);
  if (h->closed || h->q.size() >= h->max_docs ||
      h->arena_used + static_cast<size_t>(len) > h->arena_cap) {
    h->rejected++;
    // wake min_fill waiters: a queue that REJECTS pushes can't grow to
    // their fill target, so they must drain what's there instead
    h->not_empty.notify_all();
    return 0;
  }
  h->q.push_back(Doc{std::vector<uint8_t>(data, data + len), tag});
  h->arena_used += static_cast<size_t>(len);
  h->pushed++;
  h->not_empty.notify_one();
  return 1;
}

// Push n documents in one call: data is the concatenation, offsets has n+1
// entries (doc i = data[offsets[i], offsets[i+1])).  Amortises the
// per-call overhead that caps the one-at-a-time binding (~0.5M docs/s from
// Python); stops at the first rejection and returns the number accepted,
// so callers retry the remainder under backpressure.
long hb_push_many(void* hp, const uint8_t* data, const long long* offsets,
                  long n, const uint64_t* tags) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  long accepted = 0;
  for (long i = 0; i < n; ++i) {
    long long len = offsets[i + 1] - offsets[i];
    if (len < 0) break;
    if (h->closed || h->q.size() >= h->max_docs ||
        h->arena_used + static_cast<size_t>(len) > h->arena_cap) {
      h->rejected++;
      h->not_empty.notify_all();  // see hb_push: min_fill waiters must drain
      break;
    }
    const uint8_t* p = data + offsets[i];
    h->q.push_back(Doc{std::vector<uint8_t>(p, p + len), tags[i]});
    h->arena_used += static_cast<size_t>(len);
    h->pushed++;
    accepted++;
  }
  if (accepted) h->not_empty.notify_all();
  return accepted;
}

long hb_pop_batch_min(void* hp, long batch, long block_len, long timeout_ms,
                      long min_fill, uint8_t* out_tokens,
                      int32_t* out_lengths, uint64_t* out_tags);

// Fill up to `batch` rows of out_tokens (uint8[batch, block_len], zero-padded),
// out_lengths (int32[batch], truncated at block_len), out_tags
// (uint64[batch]).  Blocks up to timeout_ms for the FIRST document (0 = no
// wait, <0 = wait forever), then drains without waiting.  Returns rows
// filled; 0 means timeout or closed-and-empty.  (The min_fill=1 case of
// hb_pop_batch_min — one drain loop to maintain, not two.)
long hb_pop_batch(void* hp, long batch, long block_len, long timeout_ms,
                  uint8_t* out_tokens, int32_t* out_lengths,
                  uint64_t* out_tags) {
  return hb_pop_batch_min(hp, batch, block_len, timeout_ms, 1, out_tokens,
                          out_lengths, out_tags);
}

// Like hb_pop_batch, but waits (up to timeout_ms) until at least `min_fill`
// documents are queued before draining — the staging discipline of the
// streaming feed: a consumer that pops as soon as ONE producer chunk lands
// assembles ragged partial tiles, and every partial tile still pays a
// full-shape device kernel.  Semantics: block until q.size() >= min_fill OR
// the queue is closed OR the timeout lapses, then drain greedily (so a
// closed/timed-out queue still hands over whatever is there — progress
// beats starvation when the producer genuinely can't keep up).  min_fill
// is clamped to [1, batch]; timeout_ms < 0 waits forever, 0 never waits.
long hb_pop_batch_min(void* hp, long batch, long block_len, long timeout_ms,
                      long min_fill, uint8_t* out_tokens,
                      int32_t* out_lengths, uint64_t* out_tags) {
  auto* h = static_cast<HostBatch*>(hp);
  if (batch <= 0 || block_len <= 0) return 0;
  if (min_fill < 1) min_fill = 1;
  if (min_fill > batch) min_fill = batch;
  std::unique_lock<std::mutex> lk(h->mu);
  size_t want = static_cast<size_t>(min_fill);
  // a fill the queue can never hold (min_fill > max_docs) must not turn a
  // timeout_ms=-1 pop into a deadlock-until-close
  if (want > h->max_docs) want = h->max_docs;
  // ... and neither must backpressure: any push REJECTED while we wait
  // (doc cap or arena byte cap) proves the queue cannot reach the fill
  // target right now, so drain what's there instead of starving
  const uint64_t rej0 = h->rejected;
  if (h->q.size() < want && !h->closed && timeout_ms != 0) {
    auto ready = [h, want, rej0] {
      return h->q.size() >= want || h->closed || h->rejected != rej0;
    };
    if (timeout_ms < 0) {
      h->not_empty.wait(lk, ready);
    } else {
      h->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
    }
  }
  long n = 0;
  const size_t block = static_cast<size_t>(block_len);
  while (n < batch && !h->q.empty()) {
    Doc& d = h->q.front();
    const size_t len = d.bytes.size();
    const size_t copy = len < block ? len : block;
    uint8_t* row = out_tokens + static_cast<size_t>(n) * block;
    if (copy) std::memcpy(row, d.bytes.data(), copy);
    if (copy < block) std::memset(row + copy, 0, block - copy);
    out_lengths[n] = static_cast<int32_t>(copy);
    out_tags[n] = d.tag;
    h->arena_used -= len;
    h->popped++;
    h->q.pop_front();
    n++;
  }
  return n;
}

// Blockwise split+pad encoder: the native twin of
// core/tokenizer.encode_blocks (the host side of the ragged→fixed-shape
// bridge).  Doc i = data[offsets[i], offsets[i+1]) is cut into blocks of
// block_len bytes with `overlap` bytes carried across cuts (k-1 for
// k-shingles, so no shingle is lost at a boundary); an empty doc yields one
// zero block of recorded length 1 (parity with the Python twin's b"\x00").
// out_tokens must arrive zero-filled (np.zeros): only real bytes are
// memcpy'd, padding is never touched.  Returns blocks written, or -1 when
// the caller's count (max_blocks, computed vectorised in numpy) disagrees —
// callers treat that as a hard bug, not a retry.
long hb_encode_blocks(const uint8_t* data, const long long* offsets,
                      long n_docs, long block_len, long overlap,
                      long max_blocks, uint8_t* out_tokens,
                      int32_t* out_lengths, int32_t* out_owners) {
  if (block_len <= overlap || n_docs < 0) return -1;
  const long long stride = block_len - overlap;
  long j = 0;
  for (long i = 0; i < n_docs; ++i) {
    const long long len = offsets[i + 1] - offsets[i];
    if (len < 0) return -1;
    const uint8_t* doc = data + offsets[i];
    long long pos = 0;
    while (true) {
      if (j >= max_blocks) return -1;
      const long long rem = len - pos;
      const long long copy =
          rem < block_len ? (rem > 0 ? rem : 0) : block_len;
      if (copy)
        std::memcpy(out_tokens + static_cast<size_t>(j) * block_len,
                    doc + pos, static_cast<size_t>(copy));
      out_lengths[j] = len == 0 ? 1 : static_cast<int32_t>(copy);
      out_owners[j] = static_cast<int32_t>(i);
      ++j;
      if (pos + block_len >= len) break;
      pos += stride;
    }
  }
  return j;
}

// Range variant: encode arbitrary (start, len) byte ranges of the corpus
// blob, blockwise at block_len with `overlap` carried across cuts.  This is
// what lets the ragged dedup path route each document's TAIL block to a
// narrower width bucket (the tail of a long doc averages ~50% padding when
// stored in a full-width row) while its full blocks stay at block_len: a
// range is just "these bytes", so body and tail ranges of one document can
// encode at different widths and still reproduce exactly the block set of
// a whole-document split.  out_owners[j] = range index (callers map back).
// An empty range yields one zero block of recorded length 1 (empty-doc
// parity with hb_encode_blocks).
long hb_encode_ranges(const uint8_t* data, const long long* starts,
                      const long long* lens, long n_ranges, long block_len,
                      long overlap, long max_blocks, uint8_t* out_tokens,
                      int32_t* out_lengths, int32_t* out_owners) {
  if (block_len <= overlap || n_ranges < 0) return -1;
  const long long stride = block_len - overlap;
  long j = 0;
  for (long s = 0; s < n_ranges; ++s) {
    const long long len = lens[s];
    if (len < 0) return -1;
    const uint8_t* doc = data + starts[s];
    long long pos = 0;
    while (true) {
      if (j >= max_blocks) return -1;
      const long long rem = len - pos;
      const long long copy =
          rem < block_len ? (rem > 0 ? rem : 0) : block_len;
      if (copy)
        std::memcpy(out_tokens + static_cast<size_t>(j) * block_len,
                    doc + pos, static_cast<size_t>(copy));
      out_lengths[j] = len == 0 ? 1 : static_cast<int32_t>(copy);
      out_owners[j] = static_cast<int32_t>(s);
      ++j;
      if (pos + block_len >= len) break;
      pos += stride;
    }
  }
  return j;
}

// Single-pass exact first-seen dedup over concatenated byte items: the
// portable (blob + offsets) tier of ExactDedup, replacing pandas
// drop_duplicates' PyObject hash table.  The probe/confirm loop lives in
// bytehash.h (shared with the zero-copy tier in exactdedup.cpp); returns
// items kept, or -1 on allocation failure (callers fall back to Python).
long hb_exact_keep_first(const uint8_t* data, const long long* offsets,
                         long n, uint8_t* out_keep) {
  return bytehash::keep_first(
      n, [&](long i) { return data + offsets[i]; },
      [&](long i) { return static_cast<int64_t>(offsets[i + 1] - offsets[i]); },
      out_keep);
}

long hb_size(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return static_cast<long>(h->q.size());
}

long hb_arena_used(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return static_cast<long>(h->arena_used);
}

uint64_t hb_stat_pushed(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->pushed;
}

uint64_t hb_stat_popped(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->popped;
}

uint64_t hb_stat_rejected(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->rejected;
}

int hb_closed(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->closed ? 1 : 0;
}

// After close: pushes fail, blocked pops wake, pops drain the remainder.
void hb_close(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  h->closed = true;
  h->not_empty.notify_all();
}

void hb_destroy(void* hp) { delete static_cast<HostBatch*>(hp); }

}  // extern "C"
