// Host-side feed queue + fixed-shape batch assembler.
//
// The TPU-era successor of the reference's url_queue/result_queue plumbing
// (constant_rate_scrapper.py:146,437-469) and the C++ "host queue + batcher"
// SURVEY.md §7.3 mandates: producers (fetch/extract threads) push
// variable-length byte documents; the consumer pops fixed-shape
// uint8[batch, block] tiles with lengths + caller tags, zero-padded, ready
// for jax.device_put.  Batch assembly is memset+memcpy here so the Python
// feed thread does no per-document work at pop time.
//
// Concurrency: MPMC under one mutex (the critical sections are memcpys of
// ~1 KB documents — far from contended at the 50k docs/s north star);
// condvar wakeups for blocking pops; a byte-arena cap bounds host memory and
// gives natural backpressure (push returns 0; callers decide to block/drop).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Doc {
  std::vector<uint8_t> bytes;
  uint64_t tag;
};

struct HostBatch {
  std::mutex mu;
  std::condition_variable not_empty;
  std::deque<Doc> q;
  size_t max_docs;
  size_t arena_cap;
  size_t arena_used = 0;
  uint64_t pushed = 0;
  uint64_t popped = 0;
  uint64_t rejected = 0;
  bool closed = false;
};

}  // namespace

extern "C" {

void* hb_create(long max_docs, long arena_bytes) {
  auto* h = new HostBatch();
  h->max_docs = max_docs > 0 ? static_cast<size_t>(max_docs) : SIZE_MAX;
  h->arena_cap = arena_bytes > 0 ? static_cast<size_t>(arena_bytes) : SIZE_MAX;
  return h;
}

// 1 = accepted; 0 = queue full (backpressure) or closed.
int hb_push(void* hp, const uint8_t* data, long len, uint64_t tag) {
  auto* h = static_cast<HostBatch*>(hp);
  if (len < 0) return 0;
  std::lock_guard<std::mutex> lk(h->mu);
  if (h->closed || h->q.size() >= h->max_docs ||
      h->arena_used + static_cast<size_t>(len) > h->arena_cap) {
    h->rejected++;
    return 0;
  }
  h->q.push_back(Doc{std::vector<uint8_t>(data, data + len), tag});
  h->arena_used += static_cast<size_t>(len);
  h->pushed++;
  h->not_empty.notify_one();
  return 1;
}

// Push n documents in one call: data is the concatenation, offsets has n+1
// entries (doc i = data[offsets[i], offsets[i+1])).  Amortises the
// per-call overhead that caps the one-at-a-time binding (~0.5M docs/s from
// Python); stops at the first rejection and returns the number accepted,
// so callers retry the remainder under backpressure.
long hb_push_many(void* hp, const uint8_t* data, const long long* offsets,
                  long n, const uint64_t* tags) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  long accepted = 0;
  for (long i = 0; i < n; ++i) {
    long long len = offsets[i + 1] - offsets[i];
    if (len < 0) break;
    if (h->closed || h->q.size() >= h->max_docs ||
        h->arena_used + static_cast<size_t>(len) > h->arena_cap) {
      h->rejected++;
      break;
    }
    const uint8_t* p = data + offsets[i];
    h->q.push_back(Doc{std::vector<uint8_t>(p, p + len), tags[i]});
    h->arena_used += static_cast<size_t>(len);
    h->pushed++;
    accepted++;
  }
  if (accepted) h->not_empty.notify_all();
  return accepted;
}

// Fill up to `batch` rows of out_tokens (uint8[batch, block_len], zero-padded),
// out_lengths (int32[batch], truncated at block_len), out_tags
// (uint64[batch]).  Blocks up to timeout_ms for the FIRST document (0 = no
// wait, <0 = wait forever), then drains without waiting.  Returns rows
// filled; 0 means timeout or closed-and-empty.
long hb_pop_batch(void* hp, long batch, long block_len, long timeout_ms,
                  uint8_t* out_tokens, int32_t* out_lengths,
                  uint64_t* out_tags) {
  auto* h = static_cast<HostBatch*>(hp);
  if (batch <= 0 || block_len <= 0) return 0;
  std::unique_lock<std::mutex> lk(h->mu);
  if (h->q.empty() && !h->closed) {
    if (timeout_ms == 0) return 0;
    auto ready = [h] { return !h->q.empty() || h->closed; };
    if (timeout_ms < 0) {
      h->not_empty.wait(lk, ready);
    } else if (!h->not_empty.wait_for(
                   lk, std::chrono::milliseconds(timeout_ms), ready)) {
      return 0;
    }
  }
  long n = 0;
  const size_t block = static_cast<size_t>(block_len);
  while (n < batch && !h->q.empty()) {
    Doc& d = h->q.front();
    const size_t len = d.bytes.size();
    const size_t copy = len < block ? len : block;
    uint8_t* row = out_tokens + static_cast<size_t>(n) * block;
    if (copy) std::memcpy(row, d.bytes.data(), copy);
    if (copy < block) std::memset(row + copy, 0, block - copy);
    out_lengths[n] = static_cast<int32_t>(copy);
    out_tags[n] = d.tag;
    h->arena_used -= len;
    h->popped++;
    h->q.pop_front();
    n++;
  }
  return n;
}

// Blockwise split+pad encoder: the native twin of
// core/tokenizer.encode_blocks (the host side of the ragged→fixed-shape
// bridge).  Doc i = data[offsets[i], offsets[i+1]) is cut into blocks of
// block_len bytes with `overlap` bytes carried across cuts (k-1 for
// k-shingles, so no shingle is lost at a boundary); an empty doc yields one
// zero block of recorded length 1 (parity with the Python twin's b"\x00").
// out_tokens must arrive zero-filled (np.zeros): only real bytes are
// memcpy'd, padding is never touched.  Returns blocks written, or -1 when
// the caller's count (max_blocks, computed vectorised in numpy) disagrees —
// callers treat that as a hard bug, not a retry.
long hb_encode_blocks(const uint8_t* data, const long long* offsets,
                      long n_docs, long block_len, long overlap,
                      long max_blocks, uint8_t* out_tokens,
                      int32_t* out_lengths, int32_t* out_owners) {
  if (block_len <= overlap || n_docs < 0) return -1;
  const long long stride = block_len - overlap;
  long j = 0;
  for (long i = 0; i < n_docs; ++i) {
    const long long len = offsets[i + 1] - offsets[i];
    if (len < 0) return -1;
    const uint8_t* doc = data + offsets[i];
    long long pos = 0;
    while (true) {
      if (j >= max_blocks) return -1;
      const long long rem = len - pos;
      const long long copy =
          rem < block_len ? (rem > 0 ? rem : 0) : block_len;
      if (copy)
        std::memcpy(out_tokens + static_cast<size_t>(j) * block_len,
                    doc + pos, static_cast<size_t>(copy));
      out_lengths[j] = len == 0 ? 1 : static_cast<int32_t>(copy);
      out_owners[j] = static_cast<int32_t>(i);
      ++j;
      if (pos + block_len >= len) break;
      pos += stride;
    }
  }
  return j;
}

long hb_size(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return static_cast<long>(h->q.size());
}

long hb_arena_used(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return static_cast<long>(h->arena_used);
}

uint64_t hb_stat_pushed(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->pushed;
}

uint64_t hb_stat_popped(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->popped;
}

uint64_t hb_stat_rejected(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->rejected;
}

int hb_closed(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  return h->closed ? 1 : 0;
}

// After close: pushes fail, blocked pops wake, pops drain the remainder.
void hb_close(void* hp) {
  auto* h = static_cast<HostBatch*>(hp);
  std::lock_guard<std::mutex> lk(h->mu);
  h->closed = true;
  h->not_empty.notify_all();
}

void hb_destroy(void* hp) { delete static_cast<HostBatch*>(hp); }

}  // extern "C"
