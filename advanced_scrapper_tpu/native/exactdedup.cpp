// Exact first-seen dedup straight over a CPython list — the zero-copy tier
// of ExactDedup (pipeline/dedup.py).
//
// The portable tier (hb_exact_keep_first in hostbatch.cpp) needs the host
// to flatten the corpus into one blob + offsets first; at bench scale that
// "".join + per-item len() costs as much as the dedup itself.  This kernel
// reads each str/bytes item's buffer in place (compact-ASCII strings expose
// their bytes directly; anything else goes through the object's cached
// UTF-8 view, which is injective, so byte equality ⟺ string equality) and
// runs the same open-addressing first-seen table with full memcmp
// confirmation — no blob, no offsets, no per-item Python arithmetic.
//
// Must be called with the GIL HELD (ctypes.PyDLL, not CDLL): it touches
// Python objects throughout.  Returns the number kept, -1 on allocation
// failure, or -2 when an item isn't str/bytes or can't be UTF-8-viewed
// (lone surrogates) — callers fall back to the blob or grouping tier,
// which handle those routes.
//
// Build: g++ -O3 -shared -fPIC -I<python-include> exactdedup.cpp -o
// libexactdedup.so (driven by cpu/exactdedup.py; a failed build or load
// just disables this tier).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

#include "bytehash.h"

namespace {

// Borrowed view of an item's bytes; false when the item type is unsupported.
inline bool item_view(PyObject* o, const uint8_t** data, Py_ssize_t* len) {
  if (PyUnicode_Check(o)) {
    if (PyUnicode_IS_COMPACT_ASCII(o)) {
      *data = reinterpret_cast<const uint8_t*>(
          reinterpret_cast<PyASCIIObject*>(o) + 1);
      *len = PyUnicode_GET_LENGTH(o);
      return true;
    }
    const char* u8 = PyUnicode_AsUTF8AndSize(o, len);
    if (u8 == nullptr) {
      PyErr_Clear();  // lone surrogates etc.: signal fallback, don't raise
      return false;
    }
    *data = reinterpret_cast<const uint8_t*>(u8);
    return true;
  }
  if (PyBytes_Check(o)) {
    *data = reinterpret_cast<const uint8_t*>(PyBytes_AS_STRING(o));
    *len = PyBytes_GET_SIZE(o);
    return true;
  }
  return false;
}

}  // namespace

extern "C" {

long ed_keep_first_list(PyObject* list, uint8_t* out_keep) {
  if (!PyList_Check(list)) return -2;
  const Py_ssize_t n = PyList_GET_SIZE(list);
  if (n == 0) return 0;
  std::vector<const uint8_t*> ptrs;
  std::vector<int64_t> lens;
  try {
    ptrs.resize(n);
    lens.resize(n);
  } catch (...) {
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    const uint8_t* data;
    Py_ssize_t len;
    // str items mix with bytes items fine here: a str's UTF-8 view can
    // equal a bytes item's bytes, but pandas keeps "a" and b"a" distinct,
    // so mixed-TYPE lists must take the confirm-capable fallback tier.
    // Detect the mix cheaply: remember the first item's kind.
    if (!item_view(PyList_GET_ITEM(list, i), &data, &len)) return -2;
    if (i > 0 && PyBytes_Check(PyList_GET_ITEM(list, i)) !=
                     PyBytes_Check(PyList_GET_ITEM(list, 0)))
      return -2;
    ptrs[i] = data;
    lens[i] = len;
  }
  // probe/confirm loop shared with the blob tier (bytehash.h)
  return bytehash::keep_first(
      static_cast<long>(n), [&](long i) { return ptrs[i]; },
      [&](long i) { return lens[i]; }, out_keep);
}

}  // extern "C"
