"""Deterministic socket-plane fault injection for the lease protocol.

``ChaosTransport`` (net/transport.py) injects faults at the *fetch* plane
and ``storage.fsio.ChaosFs`` at the *storage* plane; this module closes
the third I/O plane — the TCP/NDJSON lease link (``net/lease.py``).  The
faults are the ones that kill real fleets:

- **mid-frame cut**: ``sendall`` delivers a strict prefix of the frame and
  the connection dies — the peer's line reassembler must treat the
  partial frame as garbage and the lease server must requeue everything
  the dead client still held (the half-frame-death contract).
- **trickle** (slow-loris): a frame dribbles out in tiny chunks with
  delays — correctness must not depend on a frame arriving in one
  ``recv``, and one slow client must not stall the others.
- **fragmented recv**: reads return a few bytes at a time, stressing the
  reader's reassembly the way a congested link does.

Determinism mirrors the other two planes.  Send-side faults are a pure
function of ``(seed, frame digest, per-digest occurrence)`` — NOT of a
shared random stream — so a given frame faults identically on every run
even though the lease client sends from multiple threads in
nondeterministic order (identical frames are interchangeable, so
occurrence numbering among them is order-free).  Recv-side faults key on
the per-socket call index (each socket is read by exactly one thread).
The ``ledger`` is therefore reproducible by seed up to reordering of
concurrent entries; compare it sorted.
"""

from __future__ import annotations

import threading
import time

__all__ = ["ChaosSocket", "chaos_connector"]


class ChaosSocket:
    """Fault-injecting proxy around a connected stream socket."""

    KINDS = ("cut", "trickle", "fragment")

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        cut_rate: float = 0.0,
        trickle_rate: float = 0.0,
        trickle_chunk: int = 3,
        trickle_delay: float = 0.002,
        fragment_rate: float = 0.0,
        fragment_bytes: int = 5,
    ):
        self._inner = inner
        self._seed = seed
        self._cut_rate = cut_rate
        self._trickle_rate = trickle_rate
        self._trickle_chunk = max(1, trickle_chunk)
        self._trickle_delay = trickle_delay
        self._fragment_rate = fragment_rate
        self._fragment_bytes = max(1, fragment_bytes)
        self._lock = threading.Lock()
        self._op_counts: dict[str, int] = {}
        self.injected: dict[str, int] = {k: 0 for k in self.KINDS}
        self.ledger: list[tuple[str, int, str]] = []

    # -- seeded decisions --------------------------------------------------

    def _rng(self, key: str):
        import random

        # string-seeded Random hashes its bytes (sha512): stable across
        # processes and threads, like ChaosTransport's (seed, url) scheme
        return random.Random(f"{self._seed}|{key}")

    def _next(self, op: str) -> int:
        with self._lock:
            n = self._op_counts.get(op, 0)
            self._op_counts[op] = n + 1
        return n

    def _record(self, op: str, tag, kind: str) -> None:
        with self._lock:
            self.injected[kind] += 1
            self.ledger.append((op, tag, kind))
        from advanced_scrapper_tpu.obs import telemetry, trace

        telemetry.event_counter(
            "astpu_fault_injected_total",
            "chaos faults fired, by plane and kind",
            plane="socket",
            kind=kind,
        ).inc()
        trace.record("fault", f"socket.{kind}", op=op)

    # -- faulted surface ---------------------------------------------------

    def sendall(self, data: bytes) -> None:
        import hashlib

        digest = hashlib.sha1(bytes(data)).hexdigest()[:12]
        occ = self._next(f"send|{digest}")
        r = self._rng(f"send|{digest}|{occ}")
        draw = r.random
        if self._cut_rate and draw() < self._cut_rate:
            self._record("send", (digest, occ), "cut")
            prefix = r.randrange(1, len(data)) if len(data) > 1 else 0
            if prefix:
                self._inner.sendall(data[:prefix])
            import socket as _socket

            try:
                # shutdown, not just close: another thread blocked in recv
                # holds the file description open, which would delay the
                # peer's EOF by that recv's full timeout — a real crash
                # tears the connection down NOW
                self._inner.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._inner.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"injected mid-frame cut after {prefix}/{len(data)} bytes"
            )
        if self._trickle_rate and draw() < self._trickle_rate:
            self._record("send", (digest, occ), "trickle")
            for i in range(0, len(data), self._trickle_chunk):
                self._inner.sendall(data[i : i + self._trickle_chunk])
                time.sleep(self._trickle_delay)
            return
        self._inner.sendall(data)

    def recv(self, bufsize: int) -> bytes:
        n = self._next("recv")
        if (
            self._fragment_rate
            and self._rng(f"recv|{n}").random() < self._fragment_rate
        ):
            self._record("recv", n, "fragment")
            return self._inner.recv(min(bufsize, self._fragment_bytes))
        return self._inner.recv(bufsize)

    # -- passthrough -------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self._inner, name)


def chaos_connector(**chaos_kw):
    """``connect`` factory for :class:`net.lease.LeaseClient`: dial the
    address, wrap the socket in a :class:`ChaosSocket`.  Returns
    ``(connect, sockets)`` — the list collects every wrapped socket so the
    caller can inspect the injection ledgers afterwards."""
    import socket as _socket

    sockets: list[ChaosSocket] = []

    def connect(address):
        s = ChaosSocket(
            _socket.create_connection(address, timeout=10), **chaos_kw
        )
        sockets.append(s)
        return s

    return connect, sockets
