from advanced_scrapper_tpu.net.transport import (
    FetchError,
    MockTransport,
    RequestsTransport,
    make_transport,
)

__all__ = ["FetchError", "MockTransport", "RequestsTransport", "make_transport"]
