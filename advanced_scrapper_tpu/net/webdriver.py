"""First-party W3C WebDriver wire client — stdlib only, no selenium.

The reference's entire fetch substrate is the selenium package driving the
external geckodriver binary over the WebDriver HTTP protocol
(``/root/reference/constant_rate_scrapper.py:136-139``).  selenium itself
is a thin JSON-over-HTTP client; this module implements the handful of
wire endpoints the fetch path actually uses (W3C WebDriver spec,
https://www.w3.org/TR/webdriver/), so the framework can drive
geckodriver/chromedriver directly even where the selenium package does not
exist — and so the transport stack is testable offline against a local
server speaking the real protocol (VERDICT r3 item 4) instead of
``sys.modules`` object stubs.

Endpoints used:

- ``GET  /status``                              — service readiness poll
- ``POST /session``                             — New Session (capabilities)
- ``POST /session/{id}/url``                    — Navigate To
- ``POST /session/{id}/execute/sync``           — Execute Script
- ``GET  /session/{id}/source``                 — Get Page Source
- ``POST /session/{id}/timeouts``               — Set Timeouts (pageLoad)
- ``DELETE /session/{id}``                      — Delete Session

:class:`WireSession` exposes the same driver surface the transports use
(``get`` / ``execute_script`` / ``page_source`` / ``set_page_load_timeout``
/ ``quit``), so ``net/transport.py::_WebDriverTransport`` runs unchanged on
either a selenium driver or this client.  :class:`DriverService` owns the
driver subprocess (spawn on a free port, ``/status`` readiness wait,
terminate), like selenium's ``Service``.
"""

from __future__ import annotations

import json
import socket
import subprocess
import time
import urllib.error
import urllib.request


class WebDriverError(Exception):
    """A wire-level failure; ``str(e)`` carries the driver's error code and
    message verbatim (e.g. ``unknown error: net::ERR_CONNECTION_REFUSED``)
    so the engine's circuit-breaker fingerprints
    (``pipeline/scraper.py:59-62``) keep matching exactly what real
    geckodriver/chromedriver emit."""

    def __init__(self, error: str, message: str):
        self.error = error
        self.message = message
        super().__init__(f"{error}: {message}" if message else error)


def _http_json(
    method: str, url: str, payload: dict | None, timeout: float
) -> dict:
    """One wire call.  WebDriver errors (HTTP 4xx/5xx with a JSON error
    body) raise :class:`WebDriverError`; transport-level failures raise
    ``URLError`` untouched (the caller decides what a dead driver means)."""
    data = None if payload is None else json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url,
        data=data,
        method=method,
        headers={"Content-Type": "application/json; charset=utf-8"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read().decode("utf-8", "replace")
            try:
                return json.loads(raw)
            except ValueError as e:
                # a proxy/captive portal or half-dead driver can 200 with
                # an HTML body; surface it as a wire error, not a raw
                # JSONDecodeError, so transports wrap it like any failure
                raise WebDriverError(
                    "invalid response", f"non-JSON body from {url}: {raw[:200]}"
                ) from e
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode("utf-8"))
            value = body.get("value", {})
            raise WebDriverError(
                str(value.get("error", f"http {e.code}")),
                str(value.get("message", "")),
            ) from e
        except (ValueError, KeyError, AttributeError):
            raise WebDriverError(f"http {e.code}", str(e)) from e


class WireSession:
    """One WebDriver session over the wire protocol.

    Drop-in for the selenium driver surface used by the transports.
    ``remote_url`` points at a running driver (a local geckodriver, a fake
    protocol server in tests, or a remote grid endpoint)."""

    def __init__(
        self,
        remote_url: str,
        capabilities: dict | None = None,
        timeout: float = 60.0,
    ):
        self._base = remote_url.rstrip("/")
        self._timeout = timeout
        body = _http_json(
            "POST",
            f"{self._base}/session",
            {"capabilities": {"alwaysMatch": capabilities or {}}},
            timeout,
        )
        value = body.get("value", {})
        self.session_id = value.get("sessionId") or body.get("sessionId")
        if not self.session_id:
            raise WebDriverError("session not created", json.dumps(body))
        self.capabilities = value.get("capabilities", {})

    def _url(self, suffix: str) -> str:
        return f"{self._base}/session/{self.session_id}/{suffix}"

    def get(self, url: str) -> None:
        _http_json("POST", self._url("url"), {"url": url}, self._timeout)

    def execute_script(self, script: str, *args):
        body = _http_json(
            "POST",
            self._url("execute/sync"),
            {"script": script, "args": list(args)},
            self._timeout,
        )
        return body.get("value")

    @property
    def page_source(self) -> str:
        return _http_json("GET", self._url("source"), None, self._timeout)[
            "value"
        ]

    def set_page_load_timeout(self, seconds: float) -> None:
        _http_json(
            "POST",
            self._url("timeouts"),
            {"pageLoad": int(seconds * 1000)},
            self._timeout,
        )
        # navigation can legitimately take the full pageLoad budget: give
        # the HTTP layer the same budget plus slack so the socket doesn't
        # give up before the driver does
        self._timeout = max(self._timeout, seconds + 10.0)

    def quit(self) -> None:
        _http_json(
            "DELETE",
            f"{self._base}/session/{self.session_id}",
            None,
            self._timeout,
        )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class DriverService:
    """Own a driver binary subprocess (geckodriver / chromedriver).

    Spawns ``[binary, --port=N]`` on a free port and polls ``GET /status``
    until the driver reports ready — the same contract selenium's
    ``Service`` wraps.  The ``=`` form matters: geckodriver (clap) accepts
    both ``--port N`` and ``--port=N``, but chromedriver's Chromium switch
    parser only honours ``--port=N`` — with the space form it ignores the
    value and binds its default port while the client polls a free one."""

    def __init__(
        self,
        binary: str,
        *,
        args: tuple[str, ...] = (),
        startup_timeout: float = 20.0,
    ):
        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self._proc = subprocess.Popen(
            [binary, f"--port={self.port}", *args],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + startup_timeout
        last_err: Exception | None = None
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise WebDriverError(
                    "driver exited",
                    f"{binary} exited with {self._proc.returncode} at startup",
                )
            try:
                status = _http_json("GET", f"{self.url}/status", None, 2.0)
                if status.get("value", {}).get("ready", True):
                    return
            except Exception as e:  # not listening yet
                last_err = e
            time.sleep(0.1)
        self.stop()
        raise WebDriverError(
            "driver start timeout",
            f"{binary} not ready after {startup_timeout}s ({last_err})",
        )

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=5)


FIREFOX_PREFS = {
    # the reference's browser hardening (constant_rate_scrapper.py:33-41):
    # images off, JS off, no flash
    "permissions.default.image": 2,
    "javascript.enabled": False,
    "dom.ipc.plugins.enabled.libflashplayer.so": False,
}


#: chromedriver analogues of the Firefox hardening: images and JS off
CHROME_PREFS = {
    "profile.managed_default_content_settings.images": 2,
    "profile.managed_default_content_settings.javascript": 2,
}


class _WireDriver:
    """Shared driver shell: owns an optional :class:`DriverService` and a
    :class:`WireSession`, exposing the selenium driver surface the
    transports consume.  Subclasses provide the vendor capability dict."""

    def __init__(
        self,
        executable_path: str,
        capabilities: dict,
        remote_url: str | None,
    ):
        self._service = None
        if remote_url is None:
            self._service = DriverService(executable_path)
            remote_url = self._service.url
        try:
            self._session = WireSession(remote_url, capabilities)
        except BaseException:
            if self._service is not None:
                self._service.stop()
            raise

    # -- driver surface consumed by _WebDriverTransport --
    def get(self, url: str) -> None:
        self._session.get(url)

    def execute_script(self, script: str, *args):
        return self._session.execute_script(script, *args)

    @property
    def page_source(self) -> str:
        return self._session.page_source

    def set_page_load_timeout(self, seconds: float) -> None:
        self._session.set_page_load_timeout(seconds)

    def quit(self) -> None:
        try:
            self._session.quit()
        except Exception as e:
            # a crashed/unreachable driver cannot honour Delete Session —
            # the teardown path must still terminate the process instead
            # of exploding inside every engine worker's finally block.
            # Logged, not silent: against a REMOTE driver there is no
            # service to reap, so a swallowed failure here is a leaked
            # session slot (geckodriver serves one session per process)
            import sys

            print(f"webdriver: Delete Session failed: {e}", file=sys.stderr)
        finally:
            if self._service is not None:
                self._service.stop()


class WireFirefoxDriver(_WireDriver):
    """geckodriver + headless Firefox over the wire client — the selenium
    Firefox driver surface without selenium.  Pass ``remote_url`` to attach
    to an already-running driver/grid endpoint instead of spawning one."""

    def __init__(
        self,
        executable_path: str = "geckodriver",
        *,
        headless: bool = True,
        prefs: dict | None = None,
        remote_url: str | None = None,
    ):
        opts: dict = {"prefs": dict(FIREFOX_PREFS, **(prefs or {}))}
        if headless:
            opts["args"] = ["-headless"]
        super().__init__(
            executable_path, {"moz:firefoxOptions": opts}, remote_url
        )


class WireChromeDriver(_WireDriver):
    """chromedriver + headless Chrome over the same wire protocol (the
    plain-Chrome counterpart of the reference's experimental substrate —
    anti-bot patching is :class:`StealthChromeTransport`'s job, not this
    one's)."""

    def __init__(
        self,
        executable_path: str = "chromedriver",
        *,
        headless: bool = True,
        prefs: dict | None = None,
        remote_url: str | None = None,
    ):
        opts: dict = {"prefs": dict(CHROME_PREFS, **(prefs or {}))}
        if headless:
            opts["args"] = ["--headless=new"]
        super().__init__(
            executable_path, {"goog:chromeOptions": opts}, remote_url
        )
