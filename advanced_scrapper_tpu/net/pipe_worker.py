"""Subprocess worker: line-oriented stdin/stdout JSON protocol.

Re-implements ``experiental/07_single_worker.py:38-58``: one process, one
transport; the parent writes a URL per line to stdin, the worker replies
with one JSON result line on stdout (or a JSON error object on stderr).
Configuration arrives as a JSON argv blob (``06_worker.py:24-34``):

    {"website": "yfin"}                     # plugin extractor
    {"template": {...}}                     # declarative template
    {"transport": "mock", "pages": {...}}   # test transport

Run as ``python -m advanced_scrapper_tpu.net.pipe_worker '<config json>'``.
"""

from __future__ import annotations

import json
import sys

from bs4 import BeautifulSoup


def run_worker(config: dict, stdin=None, stdout=None, stderr=None) -> None:
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr

    from advanced_scrapper_tpu.net.transport import make_transport

    transport = make_transport(
        config.get("transport", "auto"), pages=config.get("pages")
    )
    if "template" in config:
        from advanced_scrapper_tpu.extractors.template import make_template_extractor

        extractor = make_template_extractor(config["template"])
    else:
        from advanced_scrapper_tpu.extractors import load_extractor

        extractor = load_extractor(config.get("website", "yfin"))

    for line in stdin:
        url = line.strip()
        if not url:
            continue
        try:
            html = transport.fetch(url)
            data = extractor(BeautifulSoup(html, "html.parser"))
            data["url"] = url
            stdout.write(json.dumps(data) + "\n")
            stdout.flush()
        except Exception as e:
            stderr.write(json.dumps({"url": url, "error": str(e)}) + "\n")
            stderr.flush()
    transport.close()


def main() -> int:
    config = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    run_worker(config)
    return 0


if __name__ == "__main__":
    sys.exit(main())
