"""HTTP control plane: template registry + extraction service.

Re-implements the reference's Flask stack on the stdlib (Flask is not
available here):

- ``POST /add_template {"name", "template"}`` — persist a declarative
  template, create its output folder, register it as an extractor plugin
  (``01_server.py:29-41``);
- ``POST /extract_and_get_article {"url", "template"}`` — fetch + extract
  synchronously, persisting the raw ``html_source`` to
  ``<template>/<slug>.html`` and returning the extracted fields
  (``01_server.py:44-71`` + worker ``00_worker.py:36-69``); pass
  ``"async": true`` to get a ``request_id`` immediately and poll
  ``GET /get_result/<request_id>`` (the ``08_test.py:48-76`` flow, HTTP 202
  while pending — the pooled variant's 408-on-timeout becomes a clean
  poll);
- ``POST /process_url {"url", "template"}`` — the bare worker endpoint
  returning fields plus ``html_source`` (``00_worker.py:75-91``).

The in-memory results cache mirrors ``00_worker.py:72``; extraction runs on
a small thread pool like ``03_worker_multi.py``'s browser pool.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bs4 import BeautifulSoup

from advanced_scrapper_tpu.extractors.template import TemplateStore, extract_with_template


class ControlPlane:
    def __init__(
        self,
        transport_factory,
        *,
        templates_path: str = "templates.json",
        workers: int = 5,  # ref 03_worker_multi.py:31 NUM_BROWSERS
        out_root: str = ".",
    ):
        self.store = TemplateStore(templates_path)
        self.store.register_all()
        self.transport_factory = transport_factory
        self.out_root = out_root
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._results: dict[str, dict | None] = {}  # request_id → result
        self._lock = threading.Lock()
        self._local = threading.local()
        self._transports: list = []  # every created transport, for shutdown

    # -- extraction --------------------------------------------------------

    def _transport(self):
        # one transport per POOL thread (bounded by `workers`); transports
        # are tracked so shutdown() can close them — browser transports are
        # real OS processes
        t = getattr(self._local, "transport", None)
        if t is None:
            t = self.transport_factory()
            self._local.transport = t
            with self._lock:
                self._transports.append(t)
        return t

    @staticmethod
    def _check_name(name: str) -> str:
        if not name or "/" in name or "\\" in name or ".." in name or name.startswith("."):
            raise ValueError(f"invalid template name {name!r}")
        return name

    def add_template(self, name: str, template: dict) -> None:
        self.store.add(self._check_name(name), template)
        os.makedirs(os.path.join(self.out_root, name), exist_ok=True)

    def _extract_on_pool_thread(self, url: str, template_name: str) -> dict:
        template = self.store.get(self._check_name(template_name))
        html = self._transport().fetch(url)
        soup = BeautifulSoup(html, "html.parser")
        data = extract_with_template(soup, template)
        data["html_source"] = html
        return data

    def extract(self, url: str, template_name: str) -> dict:
        # Sync requests arrive on per-connection HTTP threads; run the fetch
        # on the bounded pool so transports are reused, not leaked per
        # connection.
        return self._pool.submit(
            self._extract_on_pool_thread, url, template_name
        ).result()

    def _persist_html(self, url: str, template_name: str, data: dict) -> dict:
        html = data.pop("html_source", "")
        slug = os.path.basename(url.split("?")[0].rstrip("/")) or "index"
        out_dir = os.path.join(self.out_root, template_name)
        # Templates loaded from a pre-existing templates.json (register_all on
        # restart) never went through add_template, so their folder may not
        # exist yet.
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{slug}.html")
        with open(path, "w", encoding="utf-8") as f:
            f.write(html)
        return data

    def extract_and_persist(self, url: str, template_name: str) -> dict:
        return self._persist_html(url, template_name, self.extract(url, template_name))

    def submit(self, url: str, template_name: str) -> str:
        request_id = uuid.uuid4().hex
        with self._lock:
            self._results[request_id] = None

        def work():
            try:
                data = self._extract_on_pool_thread(url, template_name)
                result = self._persist_html(url, template_name, data)
            except Exception as e:
                result = {"error": str(e)}
            with self._lock:
                self._results[request_id] = result

        self._pool.submit(work)
        return request_id

    def get_result(self, request_id: str) -> tuple[int, dict]:
        with self._lock:
            if request_id not in self._results:
                return 404, {"error": "unknown request_id"}
            result = self._results[request_id]
        if result is None:
            return 202, {"status": "pending"}
        return 200, result

    def status(self) -> dict:
        """Plane-local view for ``GET /status``: registered templates and
        the async result cache's fill."""
        with self._lock:
            pending = sum(1 for v in self._results.values() if v is None)
            done = len(self._results) - pending
        return {
            "templates": self.store.names(),
            "results_pending": pending,
            "results_done": done,
        }

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
        with self._lock:
            transports, self._transports = self._transports, []
        for t in transports:
            try:
                t.close()
            except Exception:
                pass


def make_handler(plane: ControlPlane):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code: int, obj: dict) -> None:
            body = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json_body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def do_POST(self):
            try:
                data = self._json_body()
                if self.path == "/add_template":
                    plane.add_template(data["name"], data["template"])
                    self._reply(200, {"message": "Template added successfully"})
                elif self.path == "/extract_and_get_article":
                    if data.get("async"):
                        rid = plane.submit(data["url"], data["template"])
                        self._reply(200, {"request_id": rid})
                    else:
                        self._reply(
                            200, plane.extract_and_persist(data["url"], data["template"])
                        )
                elif self.path == "/process_url":
                    self._reply(200, plane.extract(data["url"], data["template"]))
                else:
                    self._reply(404, {"error": f"no such endpoint {self.path}"})
            except KeyError as e:
                self._reply(400, {"error": f"missing field {e}"})
            except ValueError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:
                self._reply(500, {"message": f"Worker failed to process the request: {e}"})

        def do_GET(self):
            if self.path.startswith("/get_result/"):
                rid = self.path.rsplit("/", 1)[-1]
                code, obj = plane.get_result(rid)
                self._reply(code, obj)
            elif self.path == "/templates":
                self._reply(200, {"templates": plane.store.names()})
            elif self.path == "/metrics":
                # process-wide telemetry — the control plane doubles as the
                # pipeline's metrics endpoint (shared exporter: the response
                # logic lives once, in obs.telemetry)
                from advanced_scrapper_tpu.obs import telemetry

                telemetry.serve_metrics(self)
            elif self.path == "/status":
                from advanced_scrapper_tpu.obs import telemetry

                telemetry.serve_status(
                    self, extra_status=lambda: {"control": plane.status()}
                )
            else:
                self._reply(404, {"error": f"no such endpoint {self.path}"})

    return Handler


class ControlServer:
    """Threaded HTTP server wrapper around :class:`ControlPlane`."""

    def __init__(self, plane: ControlPlane, host: str = "127.0.0.1", port: int = 0):
        from advanced_scrapper_tpu.obs import telemetry

        telemetry.register_process_metrics()  # /metrics is never empty
        self.plane = plane
        self._httpd = ThreadingHTTPServer((host, port), make_handler(plane))
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "ControlServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.plane.shutdown()
