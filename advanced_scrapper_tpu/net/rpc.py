"""Length-framed, deadline-aware RPC over TCP — the fleet transport plane.

The lease protocol (``net/lease.py``) is newline-delimited JSON: right for
url strings, hopeless for posting arrays (a million uint64 band keys must
not round-trip through base64).  This module is the *binary* sibling the
index fleet rides on:

- **length-framed**: every message is ``u32 total | u32 header_len |
  header JSON | raw array bytes``; arrays are described in the header
  (dtype + shape) and travel as their exact buffer bytes — zero copies on
  send, one ``recv_into`` reassembly on receive.  Frames are capped
  (default 64 MiB) and an oversized or never-completing frame closes the
  connection and counts in telemetry — the slow-loris / unbounded-buffer
  lesson from the lease plane, applied from day one.
- **deadline-aware**: every call carries a wall-clock budget; the client
  arms the socket timeout per attempt and the server enforces a per-frame
  read deadline, so a hung peer costs a timeout, not a thread forever.
- **retry-safe**: calls are retried on connection loss / timeout with
  capped exponential backoff plus deterministic jitter, under the SAME
  request id; servers keep a bounded LRU of ``request id → response`` and
  replay instead of re-executing, so a retried ``insert`` can never
  double-apply through this layer (the shard server adds a second,
  semantic idempotency net underneath — ``index/remote.py``).

The chaos seam mirrors the lease client: ``RpcClient(connect=...)``
accepts any dialer, so ``net.chaos.chaos_connector`` puts a
:class:`~advanced_scrapper_tpu.net.chaos.ChaosSocket` under every
connection without touching protocol code.

Layering: ``net/`` must not import ``pipeline/``; ``index/`` may import
THIS module only (transport, not protocol) — both enforced by
``tools/lint_imports.py``.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Callable

import numpy as np

__all__ = [
    "DEFAULT_MAX_FRAME",
    "RpcClient",
    "RpcError",
    "RpcOverloaded",
    "RpcRemoteError",
    "RpcServer",
    "RpcUnavailable",
    "recv_frame",
    "send_frame",
]

DEFAULT_MAX_FRAME = 64 << 20  # 64 MiB: ~4M uint64 postings per frame

_LEN = struct.Struct("<I")


class RpcError(Exception):
    """Base class for every fault this layer raises."""


class RpcUnavailable(RpcError):
    """The peer could not be reached / answered within the deadline after
    every retry.  The fleet client treats this as a node failure (failover
    or spill); it never means the request semantically failed."""


class RpcRemoteError(RpcError):
    """The handler on the peer raised.  Never retried — the request
    *reached* the peer and failed deterministically."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype


class RpcOverloaded(RpcError):
    """The peer refused admission (overload shed), answering a counted
    reject with a ``retry_after`` hint instead of timing out.  Retriable
    — the handler never executed — and NEVER a node-death signal: the
    fleet client backs off in place on this, it must not fail over or
    promote (an overloaded-but-alive shard failed over would dump its
    load onto the survivors and cascade)."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message)
        self.retry_after = max(0.0, float(retry_after))


class FrameTooLarge(RpcError):
    """A peer announced (or sent) a frame beyond the cap."""


def _count_frame_drop(kind: str) -> None:
    from advanced_scrapper_tpu.obs import telemetry

    telemetry.event_counter(
        "astpu_rpc_frames_dropped_total",
        "RPC frames dropped by the framing guards, by reason",
        reason=kind,
    ).inc()


def send_frame(sock, header: dict, arrays=()) -> None:
    """One framed message: header JSON + the raw bytes of each array.

    Array wire metadata (dtype/shape) goes into the header under
    ``_arrays``; callers never put binary in the JSON.
    """
    metas = []
    bufs = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        metas.append({"dtype": a.dtype.str, "shape": list(a.shape)})
        bufs.append(a.tobytes())
    h = dict(header)
    h["_arrays"] = metas
    hb = json.dumps(h).encode("utf-8")
    body_len = _LEN.size + len(hb) + sum(len(b) for b in bufs)
    sock.sendall(
        b"".join([_LEN.pack(body_len), _LEN.pack(len(hb)), hb, *bufs])
    )


def _read_exact(sock, n: int, deadline: float | None) -> bytes:
    """Read exactly ``n`` bytes or raise; the deadline bounds the WHOLE
    read, so a peer dribbling one byte per timeout window (slow-loris)
    still gets cut off at the frame budget."""
    parts: list[bytes] = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("frame read deadline exceeded")
            sock.settimeout(min(remaining, 10.0))
        # plain recv, not recv_into: ChaosSocket's fragmented-read fault
        # intercepts recv, so the reassembly below is what chaos stresses
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def recv_frame(
    sock,
    *,
    max_frame: int = DEFAULT_MAX_FRAME,
    frame_deadline: float | None = None,
) -> tuple[dict, list[np.ndarray]] | None:
    """Read one frame → ``(header, arrays)``; ``None`` on clean EOF.

    ``frame_deadline`` is seconds allowed for the whole frame once its
    length prefix arrived.  Oversized frames raise :class:`FrameTooLarge`
    after counting the drop — the caller must close the connection (the
    stream position is unrecoverable by design).
    """
    # the wait for the FIRST byte runs under whatever timeout the caller
    # armed (the server's idle timeout, the client's call budget); the
    # frame deadline starts once the length prefix begins arriving
    first = sock.recv(_LEN.size)
    if not first:
        return None
    deadline = (
        time.monotonic() + frame_deadline if frame_deadline is not None else None
    )
    if len(first) < _LEN.size:
        first += _read_exact(sock, _LEN.size - len(first), deadline)
    (body_len,) = _LEN.unpack(first)
    if body_len > max_frame:
        _count_frame_drop("oversize")
        raise FrameTooLarge(f"frame of {body_len} bytes exceeds cap {max_frame}")
    body = _read_exact(sock, body_len, deadline)
    (hlen,) = _LEN.unpack_from(body, 0)
    if hlen > body_len - _LEN.size:
        _count_frame_drop("malformed")
        raise RpcError(f"header length {hlen} exceeds frame body {body_len}")
    header = json.loads(body[_LEN.size : _LEN.size + hlen].decode("utf-8"))
    arrays: list[np.ndarray] = []
    off = _LEN.size + hlen
    for meta in header.pop("_arrays", []):
        dt = np.dtype(meta["dtype"])
        count = int(np.prod(meta["shape"])) if meta["shape"] else 1
        nbytes = dt.itemsize * count
        if off + nbytes > len(body):
            _count_frame_drop("malformed")
            raise RpcError("array bytes exceed frame body")
        arrays.append(
            np.frombuffer(body, dt, count=count, offset=off).reshape(
                meta["shape"]
            )
        )
        off += nbytes
    return header, arrays


def backoff_delays(
    attempts: int, *, base: float, cap: float, seed
) -> list[float]:
    """Capped exponential backoff with deterministic full jitter: delay
    ``i`` is uniform in ``(0, min(cap, base·2^i)]``, drawn from a RNG
    seeded by ``seed`` so a given (client, request) retries identically
    on every run — the chaos-certification requirement."""
    import random

    r = random.Random(f"rpc-backoff|{seed}")
    return [
        r.uniform(0, min(cap, base * (2.0**i))) or base
        for i in range(max(0, attempts))
    ]


class RpcServer:
    """Threaded RPC endpoint: one handler table, one idempotency cache.

    ``handlers`` maps method name → ``fn(header, arrays) -> (header,
    arrays)`` (returning a bare dict means no arrays).  A raising handler
    answers an error frame; the connection survives.  A malformed,
    oversized or deadline-blowing frame kills ONLY that connection.

    Every server answers ``__ping__`` natively — the health-check /
    promotion probe needs no handler wiring.

    **Admission** (``admission=``, a
    :class:`~advanced_scrapper_tpu.runtime.admission.AdmissionController`):
    each request to a gated method (``admission_methods``; None = all)
    must be admitted before it may claim the idempotency table or run a
    handler; a refusal answers a counted ``RpcOverloaded`` error frame
    carrying the retry-after hint, and is deliberately NOT cached under
    the request id — the same id retried later must get a fresh
    admission decision.  ``__ping__`` always bypasses admission: an
    overloaded server must stay provably alive, or overload becomes
    indistinguishable from death and triggers failover.
    ``method_priority`` maps method → priority class (default NORMAL).

    ``admission_resolver`` is the PER-REQUEST half of the same gate: a
    callable ``(method, header) -> (controller, priority) | None``
    consulted before the static controller (the service gateway resolves
    the request's tenant id to that tenant's own token bucket here).  A
    resolved gate stacks UNDER the shared one — it is admitted first and
    refused first, so a tenant over its quota is stopped at its own
    bucket (billed to its own pressure series) without consuming a
    shared slot, and its refusal rides the exact same uncached,
    retry-after-carrying ``RpcOverloaded`` path.  Resolving through the
    header rather than raising inside a handler is load-bearing: handler
    exceptions are remembered under the request id and would replay a
    stale refusal at the client's retry.
    """

    def __init__(
        self,
        handlers: dict[str, Callable],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame: int = DEFAULT_MAX_FRAME,
        frame_deadline: float = 30.0,
        idle_timeout: float = 300.0,
        idempotent_cache: int = 512,
        name: str = "rpc",
        admission=None,
        admission_methods=None,
        method_priority: dict[str, int] | None = None,
        admission_resolver=None,
    ):
        self.handlers = dict(handlers)
        self.admission = admission
        self.admission_resolver = admission_resolver
        self.admission_methods = (
            None if admission_methods is None else frozenset(admission_methods)
        )
        self.method_priority = dict(method_priority or {})
        self.host = host
        self.port = port
        self.max_frame = max_frame
        self.frame_deadline = frame_deadline
        self.idle_timeout = idle_timeout
        self.name = name
        self._cache_cap = idempotent_cache
        self._cache: dict[str, tuple[dict, list]] = {}
        self._cache_order: list[str] = []
        self._cache_lock = threading.Lock()
        self._inflight: dict[str, threading.Event] = {}
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self.calls = 0          # handler executions (not replays)
        self.replays = 0        # idempotent cache hits
        self.overload_rejects = 0  # admission refusals answered
        self._instrument()

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._m_calls = telemetry.counter(
            "astpu_rpc_server_calls_total", "handler executions", server=self.name
        )
        self._m_replays = telemetry.counter(
            "astpu_rpc_server_replays_total",
            "duplicate request ids answered from the idempotency cache",
            server=self.name,
        )
        self._m_errors = telemetry.counter(
            "astpu_rpc_server_errors_total", "handler exceptions answered as errors",
            server=self.name,
        )
        # always-on (like every admission counter): an overload reject
        # during an incident must be visible with telemetry off
        self._m_overload: dict[str, object] = {}  # method → reject counter
        self._m_seconds: dict[str, object] = {}  # method → latency histogram

    def _overload_counter(self, method: str):
        c = self._m_overload.get(method)
        if c is None:
            from advanced_scrapper_tpu.obs import telemetry

            c = telemetry.REGISTRY.counter(
                "astpu_rpc_overload_rejects_total",
                "requests refused admission and answered RpcOverloaded",
                always=True, server=self.name, method=method,
            )
            self._m_overload[method] = c
        return c

    def _method_seconds(self, method: str):
        """Per-method server-side latency histogram (lazy: the method set
        is the handler table, but only methods actually called pay a
        series).  Observations carry the propagated trace id as a
        slow-call exemplar, so a p99 outlier on ``/metrics`` names the
        stitched trace that caused it."""
        h = self._m_seconds.get(method)
        if h is None:
            from advanced_scrapper_tpu.obs import telemetry

            h = telemetry.histogram(
                "astpu_rpc_server_seconds",
                "server-side handler wall clock, by method",
                server=self.name,
                method=method,
            )
            self._m_seconds[method] = h
        return h

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RpcServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._sock.settimeout(0.5)
        t = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-accept-{self.name}"
        )
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        # sever live connections too: a stopped server must look DEAD to
        # its peers (transport fault → failover), never answer from
        # torn-down state behind a still-open socket
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            # prune finished handlers: a long-lived shard server under a
            # reconnect-happy client must not accumulate dead Thread
            # objects (and stop() must not join thousands of them)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # -- request handling --------------------------------------------------

    def _cached(self, rid: str):
        with self._cache_lock:
            return self._cache.get(rid)

    def _remember(self, rid: str, resp) -> None:
        with self._cache_lock:
            if rid not in self._cache:
                self._cache[rid] = resp
                self._cache_order.append(rid)
                while len(self._cache_order) > self._cache_cap:
                    self._cache.pop(self._cache_order.pop(0), None)
            ev = self._inflight.pop(rid, None)
        if ev is not None:
            ev.set()

    def _claim(self, rid: str):
        """Idempotency admission, atomic with the cache check: returns
        ``("hit", resp)``, ``("mine", None)`` (this thread executes), or
        ``("wait", event)`` (a duplicate of a request STILL RUNNING —
        waiting closes the check-then-execute race where a timeout retry
        lands while the first execution is in flight)."""
        with self._cache_lock:
            hit = self._cache.get(rid)
            if hit is not None:
                return "hit", hit
            ev = self._inflight.get(rid)
            if ev is not None:
                return "wait", ev
            self._inflight[rid] = threading.Event()
            return "mine", None

    def _serve_conn(self, conn: socket.socket) -> None:
        from advanced_scrapper_tpu.obs import trace as _trace

        with self._conns_lock:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                conn.settimeout(self.idle_timeout)
                try:
                    frame = recv_frame(
                        conn,
                        max_frame=self.max_frame,
                        frame_deadline=self.frame_deadline,
                    )
                except socket.timeout:
                    _count_frame_drop("deadline")
                    return  # slow-loris / idle peer: cut it loose
                except (FrameTooLarge, RpcError):
                    return  # counted inside recv_frame; stream unusable
                if frame is None:
                    return
                header, arrays = frame
                rid = header.get("id")
                method = header.get("method", "")
                # propagated trace context (popped: handlers never see the
                # transport's trace plumbing in their header dict)
                tctx = _trace.context_from_wire(header.pop("_trace", None))
                if not self._handle_request(
                    conn, header, arrays, rid, method, tctx
                ):
                    return
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _unclaim(self, rid: str) -> None:
        """Withdraw an in-flight claim that will never execute (admission
        refused it): wake any waiting duplicate — it finds no cached
        response, drops its connection, and the NEXT retry claims and
        re-attempts admission fresh."""
        with self._cache_lock:
            ev = self._inflight.pop(rid, None)
        if ev is not None:
            ev.set()

    def _handle_request(self, conn, header, arrays, rid, method, tctx) -> bool:
        """Claim → admit → execute-or-replay → respond for one request.
        Returns False when the connection must be dropped (a
        still-in-flight duplicate outlived the frame budget).

        Order is load-bearing: the idempotency claim comes FIRST, so the
        replay and wait-for-duplicate paths — which run no handler —
        never consume an admission slot (a retried slow insert parked in
        ``val.wait`` must not hold a ``max_inflight`` seat and amplify
        the very storm admission damps); only the "mine" executor pays
        admission, and a refusal withdraws the claim so waiters and
        later retries get a fresh decision."""
        from advanced_scrapper_tpu.obs import trace as _trace

        if rid is not None:
            state, val = self._claim(rid)
            if state == "hit":
                self.replays += 1
                self._m_replays.inc()
                # the retry carried the SAME trace header as the
                # original attempt; record the replay under it so
                # a stitched trace shows the dedup, not a gap
                _trace.record(
                    "event", "rpc.replay",
                    server=self.name, method=method, rid=rid,
                    **({"trace": tctx[0]} if tctx else {}),
                )
                send_frame(conn, val[0], val[1])
                return True
            if state == "wait":
                # a timeout retry of a request whose FIRST
                # execution is still running: executing again
                # would double-apply, so wait for its result and
                # replay; if it outlives the frame budget, drop
                # this connection — the next retry finds the cache
                if val.wait(self.frame_deadline):
                    hit = self._cached(rid)
                    if hit is not None:
                        self.replays += 1
                        self._m_replays.inc()
                        send_frame(conn, hit[0], hit[1])
                        return True
                return False
        gates: list = []  # (controller, priority); per-request gate FIRST
        if self.admission_resolver is not None and method != "__ping__":
            resolved = self.admission_resolver(method, header)
            if resolved is not None:
                gates.append(resolved)
        if (
            self.admission is not None
            and method != "__ping__"
            and (
                self.admission_methods is None
                or method in self.admission_methods
            )
        ):
            from advanced_scrapper_tpu.runtime.admission import (
                PRIORITY_NORMAL,
            )

            gates.append((
                self.admission,
                self.method_priority.get(method, PRIORITY_NORMAL),
            ))
        admitted: list = []  # (controller, decision) already holding slots
        for ctrl, prio in gates:
            adm = ctrl.admit(prio)
            if adm.admitted:
                admitted.append((ctrl, adm))
                continue
            # counted reject + retry-after hint.  Deliberately NOT
            # remembered under rid (claim withdrawn): a later retry
            # of the same request must get a fresh admission
            # decision, never a replayed refusal.  Slots already
            # taken from earlier gates are handed back — a refusal
            # must never leak inflight seats.
            for held_ctrl, held in admitted:
                held_ctrl.release(held)
            if rid is not None:
                self._unclaim(rid)
            self.overload_rejects += 1
            self._overload_counter(method).inc()
            send_frame(
                conn,
                {
                    "id": rid,
                    "error": (
                        f"{self.name}: {method} refused "
                        f"admission ({adm.reason})"
                    ),
                    "etype": "RpcOverloaded",
                    "retry_after": adm.retry_after,
                },
            )
            return True
        try:
            return self._execute_and_respond(
                conn, header, arrays, rid, method, tctx
            )
        finally:
            for ctrl, adm in admitted:
                ctrl.release(adm)

    def _execute_and_respond(
        self, conn, header, arrays, rid, method, tctx
    ) -> bool:
        from advanced_scrapper_tpu.obs import trace as _trace

        resp_h: dict
        resp_a: list = []
        if method == "__ping__":
            resp_h = {"id": rid, "ok": True, "pong": True}
        elif method not in self.handlers:
            resp_h = {
                "id": rid,
                "error": f"no such method {method!r}",
                "etype": "KeyError",
            }
        else:
            # server-side span under the PROPAGATED context: the
            # handler thread has no ambient trace of its own, so a
            # span here carrying the client's trace id proves the
            # id crossed the socket — the stitched-trace half of
            # the observability plane
            t0 = time.perf_counter()
            try:
                with _trace.trace_context(*(tctx or (None, None))):
                    with _trace.span(
                        f"rpc.{method}", server=self.name, rid=rid
                    ):
                        out = self.handlers[method](header, arrays)
                if isinstance(out, tuple):
                    resp_h, resp_a = dict(out[0]), list(out[1])
                else:
                    resp_h, resp_a = dict(out or {}), []
                resp_h.setdefault("ok", True)
                resp_h["id"] = rid
                self.calls += 1
                self._m_calls.inc()
            except Exception as e:  # answered, not fatal
                self._m_errors.inc()
                resp_h = {
                    "id": rid,
                    "error": str(e),
                    "etype": type(e).__name__,
                }
            self._method_seconds(method).observe(
                time.perf_counter() - t0,
                trace=tctx[0] if tctx else None,
            )
        # remember BEFORE sending: a cut mid-response must replay
        # the same bytes, not re-execute the handler
        if rid is not None:
            self._remember(rid, (resp_h, resp_a))
        send_frame(conn, resp_h, resp_a)
        return True


class RpcClient:
    """One connection to one RPC endpoint, with retry + reconnect.

    Thread-safe: one in-flight call at a time (a lock serialises the
    frame exchange); the fleet client holds one ``RpcClient`` per node
    and fans out across nodes with threads, not across one socket.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        address: tuple[str, int],
        *,
        timeout: float = 10.0,
        retries: int = 3,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        max_frame: int = DEFAULT_MAX_FRAME,
        connect: Callable | None = None,
        seed: int = 0,
        sleep=time.sleep,
        overload_wait_cap: float = 5.0,
    ):
        self.address = tuple(address)
        self.timeout = timeout
        self.retries = retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        #: ceiling on any single retry-after honor: a peer hinting "come
        #: back in 200 s" (a triggered pause, a near-zero insert_rate)
        #: must not park one call() for that long — the client sleeps at
        #: most this, retries, and surfaces RpcOverloaded (hint intact)
        #: if still refused, letting the CALLER's budget discipline rule
        self.overload_wait_cap = float(overload_wait_cap)
        self.max_frame = max_frame
        self.sleep = sleep
        self._connect = connect
        self._seed = seed
        self._sock = None
        self._lock = threading.Lock()
        with RpcClient._seq_lock:
            self._cid = RpcClient._seq
            RpcClient._seq += 1
        # random token: request ids must be unique ACROSS processes — the
        # server's idempotency cache is global per server, and two worker
        # processes both counting from c0-1 would replay each other's
        # cached responses for unrelated requests
        import os as _os

        self._token = _os.urandom(4).hex()
        self._rid = 0
        self._instrument()

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._m_retries = telemetry.counter(
            "astpu_rpc_client_retries_total",
            "call attempts beyond the first (timeouts + connection faults)",
        )
        # always-on: overload behaviour must be auditable in an incident
        # (the loadgen/crashsweep acceptance reads these to prove the
        # client actually honored the server's retry-after hints)
        self._m_overloaded = telemetry.REGISTRY.counter(
            "astpu_rpc_client_overloaded_total",
            "responses refused admission by the peer (RpcOverloaded)",
            always=True,
        )
        self._m_overload_wait = telemetry.REGISTRY.counter(
            "astpu_rpc_overload_backoff_seconds_total",
            "seconds slept honoring peer retry-after hints",
            always=True,
        )

    # -- connection --------------------------------------------------------

    def _dial(self):
        if self._connect is not None:
            return self._connect(self.address)
        return socket.create_connection(self.address, timeout=self.timeout)

    def _ensure_sock(self):
        if self._sock is None:
            self._sock = self._dial()
        return self._sock

    def close(self) -> None:
        with self._lock:
            self._drop_sock()

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- calls -------------------------------------------------------------

    def next_request_id(self) -> str:
        with self._lock:
            self._rid += 1
            return f"c{self._token}.{self._cid}-{self._rid}"

    def call(
        self,
        method: str,
        header: dict | None = None,
        arrays=(),
        *,
        timeout: float | None = None,
        idempotent: bool = True,
        request_id: str | None = None,
    ):
        """One RPC → ``(header, arrays)``.

        Connection faults and deadline misses retry (idempotent calls
        only) under the SAME request id with capped jittered backoff;
        :class:`RpcRemoteError` (handler raised) never retries.  The
        request id may be supplied by the caller — how the fleet's spill
        replay reuses the ORIGINAL id, so a posting spilled after a
        half-delivered insert still cannot double-apply.
        """
        rid = request_id or self.next_request_id()
        budget = self.timeout if timeout is None else timeout
        req = dict(header or {})
        req["id"] = rid
        req["method"] = method
        # trace propagation: the ambient context rides the request header,
        # FIXED across retries (the header is built once) — a retried call
        # replayed from the server cache still belongs to the same trace
        from advanced_scrapper_tpu.obs import trace as _trace

        tctx = _trace.wire_context()
        if tctx is not None:
            req["_trace"] = tctx
        attempts = (self.retries + 1) if idempotent else 1
        delays = backoff_delays(
            attempts - 1,
            base=self.backoff_base,
            cap=self.backoff_cap,
            seed=f"{self._seed}|{rid}",
        )
        # overload backoffs have their own budget (retriable even for
        # non-idempotent calls — the handler never executed) and their
        # own deterministic jitter stream; the peer's retry-after hint is
        # the floor of every wait
        ov_delays = backoff_delays(
            self.retries,
            base=self.backoff_base,
            cap=self.backoff_cap,
            seed=f"{self._seed}|{rid}|overload",
        )
        last: Exception | None = None
        transport_tries = 0
        overload_tries = 0
        while True:
            try:
                with self._lock:
                    sock = self._ensure_sock()
                    sock.settimeout(budget)
                    send_frame(sock, req, arrays)
                    resp = recv_frame(
                        sock, max_frame=self.max_frame, frame_deadline=budget
                    )
                if resp is None:
                    raise ConnectionError("server closed the connection")
                h, a = resp
                if h.get("error") is not None:
                    if h.get("etype") == "RpcOverloaded":
                        raise RpcOverloaded(
                            h["error"], h.get("retry_after", 0.0)
                        )
                    raise RpcRemoteError(h.get("etype", "Error"), h["error"])
                return h, a
            except RpcOverloaded as e:
                # counted reject from the peer: back off at least its
                # retry-after hint and retry under the SAME request id —
                # never a node failure, so never RpcUnavailable
                overload_tries += 1
                self._m_overloaded.inc()
                if overload_tries > self.retries:
                    raise
                wait = min(
                    max(e.retry_after, ov_delays[overload_tries - 1]),
                    self.overload_wait_cap,
                )
                self._m_overload_wait.inc(wait)
                self.sleep(wait)
            except RpcRemoteError:
                raise
            except (ConnectionError, OSError, socket.timeout, RpcError) as e:
                last = e
                with self._lock:
                    self._drop_sock()
                transport_tries += 1
                if transport_tries >= attempts:
                    break
                self._m_retries.inc()
                self.sleep(delays[transport_tries - 1])
        raise RpcUnavailable(
            f"{method} to {self.address} failed after {attempts} attempts: {last}"
        )

    def ping(self, *, timeout: float | None = None) -> bool:
        """Health probe; False on any transport fault, never raises."""
        try:
            h, _ = self.call(
                "__ping__", timeout=timeout if timeout is not None else 2.0
            )
            return bool(h.get("pong"))
        except RpcError:
            return False
