"""Distributed pull-based work leasing over TCP + NDJSON.

Re-implements the reference's only real multi-node backend
(``experiental/server1.py`` / ``client1.py``, SURVEY.md §5.8):

- newline-delimited JSON protocol:
  ``request_tasks{num_urls}`` → ``task_batch{urls}`` (server ``:102-116``),
  ``result{url, html_content}`` (``:117-124``),
  ``tasks_completed`` → ``acknowledge_completion`` (``:125-130``);
- **lease fault tolerance**: every url handed to a client is tracked in its
  assigned set and returned to the queue if the client disconnects before
  reporting it (``:80-84,137-138``) — pull-based work stealing;
- client keeps its local queue topped up: request ``batch_size`` urls
  whenever depth < ``min_queue_length``, rate-capped (client ``:209-234``);
- clients ship raw HTML (or ``ERROR:``-prefixed strings) back; the server
  parses centrally with the extractor plugin and writes the standard
  success/failed CSVs (``:232-309``).

This is also the host feed scheduler pattern the north star reuses at the
CPU→TPU boundary: the server side can hand its parsed results straight to
``extractors.tpu_batch.TpuBatchBackend``.

In the TPU-native framework the *device* plane scales via jax.distributed +
collectives (``parallel/``); this module is the *host* plane that feeds it.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from typing import Callable

from bs4 import BeautifulSoup

from advanced_scrapper_tpu.config import FeedConfig
from advanced_scrapper_tpu.obs.stats import RateStats
from advanced_scrapper_tpu.runtime import Edge


def _send_json(sock: socket.socket, lock: threading.Lock, obj: dict) -> None:
    data = (json.dumps(obj) + "\n").encode("utf-8")
    with lock:
        sock.sendall(data)


class FrameTooLong(ValueError):
    """A peer exceeded the line-reassembly cap without sending a newline."""


class _LineReader:
    """Reassemble newline-framed JSON from a stream socket (client ``:146-181``).

    ``max_line`` caps the reassembly buffer: a peer that streams bytes
    without ever framing them (malice, corruption, or a runaway payload)
    previously grew ``self.buf`` without bound.  Exceeding the cap counts
    the drop in telemetry and raises :class:`FrameTooLong` — the caller
    must close the connection (the stream position is unrecoverable)."""

    def __init__(self, sock: socket.socket, max_line: int = 16 << 20):
        self.sock = sock
        self.buf = b""
        self.max_line = max_line

    def readline(self) -> dict | None:
        while b"\n" not in self.buf:
            if len(self.buf) > self.max_line:
                from advanced_scrapper_tpu.obs import telemetry

                telemetry.event_counter(
                    "astpu_lease_oversize_frames_total",
                    "connections cut for exceeding the line-frame cap",
                ).inc()
                raise FrameTooLong(
                    f"{len(self.buf)} unframed bytes exceed the "
                    f"{self.max_line} B line cap"
                )
            chunk = self.sock.recv(65536)
            if not chunk:
                return None
            self.buf += chunk
        line, self.buf = self.buf.split(b"\n", 1)
        if not line.strip():
            return self.readline()
        return json.loads(line.decode("utf-8"))


class LeaseServer:
    """Task server: leases url batches, collects results, survives client loss."""

    def __init__(
        self,
        cfg: FeedConfig,
        urls: list[str],
        *,
        host: str | None = None,
        port: int | None = None,
        status_port: int | None = None,
        admission=None,
    ):
        """``status_port`` mirrors the control plane's observability
        endpoints (``GET /metrics`` + ``GET /status``) on a small HTTP
        server beside the TCP lease socket: 0 = ephemeral port, None =
        only when telemetry is enabled (``ASTPU_TELEMETRY``).

        ``admission`` (an
        :class:`~advanced_scrapper_tpu.runtime.admission.AdmissionController`)
        sheds lease *grants* under pressure: a refused ``request_tasks``
        gets an EMPTY ``task_batch`` carrying ``shed: true`` and a
        ``retry_after`` hint — the client backs its refill loop off
        instead of hammering, results/heartbeats flow untouched, and the
        shed is counted.  Leases already held are never reclaimed by
        admission (that is the TTL reaper's job)."""
        self.cfg = cfg
        self.admission = admission
        self.host = host if host is not None else cfg.host
        self.port = port if port is not None else cfg.port
        self._status_port = status_port
        self.status_server = None
        # the work queue is a runtime Edge: the scheduler's depth/stall
        # telemetry (astpu_edge_*{graph="lease"}) and the crash snapshot
        # see the lease plane's backlog exactly like a local stage's
        self._urls: Edge = Edge("urls", graph="lease")
        # dedup on ingest: a url is one unit of work (the per-client
        # assigned sets — and the stray-result guard built on them — are
        # keyed by url, so a duplicated input row would leave a pending
        # count that can never drain)
        seen: set[str] = set()
        for u in urls:
            if u not in seen:
                seen.add(u)
                self._urls.put(u)
        self._pending = len(seen)
        self._assigned: dict[int, set[str]] = {}
        self._last_seen: dict[int, float] = {}   # cid → monotonic stamp of
        #   the last COMPLETE frame (heartbeats count; dribbled bytes don't)
        self._conns: dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self.results: list[dict] = []
        self.stats = RateStats()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._sock: socket.socket | None = None
        self._next_client = 0
        self._instrument()

    _seq_lock = threading.Lock()
    _seq = 0

    def _instrument(self) -> None:
        """Per-worker fleet gauges + protocol counters.  The per-client
        assigned counts export as ONE expanding callback gauge, so a fleet
        of N workers is N series on ``/metrics`` without per-connect
        registration churn.  An EXPLICIT ``status_port`` forces the lease
        instrumentation live even when ``ASTPU_TELEMETRY`` is off — an
        operator who asked for the mirror must not scrape an empty one.
        Per-instance ``server=`` label: concurrent lease servers in one
        process must not replace each other's series."""
        from advanced_scrapper_tpu.obs import telemetry

        always = self._status_port is not None
        with LeaseServer._seq_lock:
            sid = str(LeaseServer._seq)
            LeaseServer._seq += 1
        self._m_leased = telemetry.REGISTRY.counter(
            "astpu_lease_urls_leased_total", "urls handed to clients",
            always=always, server=sid,
        )
        self._m_results = telemetry.REGISTRY.counter(
            "astpu_lease_results_total", "results accepted from clients",
            always=always, server=sid,
        )
        self._m_stray = telemetry.REGISTRY.counter(
            "astpu_lease_stray_results_total",
            "duplicate/stray results rejected by the assignment guard",
            always=always, server=sid,
        )
        self._m_requeued = telemetry.REGISTRY.counter(
            "astpu_lease_urls_requeued_total",
            "urls returned to the queue by client disconnects",
            always=always, server=sid,
        )
        self._m_ttl_expired = telemetry.REGISTRY.counter(
            "astpu_lease_ttl_expired_total",
            "clients whose leases were reclaimed on heartbeat timeout "
            "(hung-but-connected workers)",
            always=always, server=sid,
        )
        self._m_shed = telemetry.REGISTRY.counter(
            "astpu_lease_shed_grants_total",
            "lease requests refused admission under pressure (answered "
            "empty with a retry-after hint)",
            always=True, server=sid,
        )
        telemetry.gauge_fn(
            "astpu_lease_pending",
            lambda s: s._pending,
            owner=self,
            always=always,
            help="urls not yet successfully resulted",
            server=sid,
        )
        telemetry.gauge_fn(
            "astpu_lease_clients_connected",
            lambda s: len(s._assigned),
            owner=self,
            always=always,
            help="clients with an open assignment ledger",
            server=sid,
        )
        telemetry.gauge_fn(
            "astpu_lease_assigned",
            lambda s: {
                cid: len(urls) for cid, urls in s._assigned_snapshot().items()
            },
            owner=self,
            expand="client",
            always=always,
            help="urls currently leased per client",
            server=sid,
        )
        telemetry.gauge_fn(
            "astpu_lease_request_rate",
            lambda s: s.stats.rates()[0],
            owner=self,
            always=always,
            help="task requests/s over the stats window",
            server=sid,
        )
        telemetry.gauge_fn(
            "astpu_lease_response_rate",
            lambda s: s.stats.rates()[1],
            owner=self,
            always=always,
            help="results/s over the stats window",
            server=sid,
        )

    def _assigned_snapshot(self) -> dict[int, set[str]]:
        with self._lock:
            return {cid: set(urls) for cid, urls in self._assigned.items()}

    def fleet_status(self) -> dict:
        """JSON-able fleet view — merged into the status endpoint's payload
        and directly usable by dashboards."""
        req_rate, resp_rate = self.stats.rates()
        with self._lock:
            return {
                "pending": self._pending,
                "clients": {
                    str(cid): len(urls) for cid, urls in self._assigned.items()
                },
                "results": len(self.results),
                "request_rate": round(req_rate, 2),
                "response_rate": round(resp_rate, 2),
            }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LeaseServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        if self.port == 0:
            self.port = self._sock.getsockname()[1]
        self._sock.listen(self.cfg.max_clients)
        self._sock.settimeout(0.5)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        if self.cfg.lease_ttl > 0:
            r = threading.Thread(target=self._ttl_reaper, daemon=True)
            r.start()
            self._threads.append(r)
        from advanced_scrapper_tpu.obs import telemetry

        if self._status_port is not None or telemetry.enabled():
            self.status_server = telemetry.StatusServer(
                port=self._status_port or 0,
                name=f"lease-{self.port}",
                extra_status=lambda: {"lease": self.fleet_status()},
            ).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        for t in self._threads:
            t.join(timeout=5)
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None

    def done(self) -> bool:
        with self._lock:
            return self._pending <= 0

    def wait_done(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.done():
                return True
            time.sleep(0.05)
        return False

    # -- accept / client handling -----------------------------------------

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                cid = self._next_client
                self._next_client += 1
                self._assigned[cid] = set()
                self._last_seen[cid] = time.monotonic()
                self._conns[cid] = conn
            t = threading.Thread(
                target=self._handle_client, args=(conn, cid), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _lease(self, cid: int, n: int) -> list[str]:
        out = []
        with self._lock:
            # setdefault: a TTL-expired client that wakes up and keeps
            # requesting gets a fresh ledger (its old leases were already
            # requeued; its connection is being torn down, so these new
            # leases flow back via the normal disconnect return)
            ledger = self._assigned.setdefault(cid, set())
            for _ in range(n):
                try:
                    u = self._urls.get_nowait()
                except queue.Empty:
                    break
                out.append(u)
                ledger.add(u)
        self._m_leased.inc(len(out))
        return out

    def _return_unprocessed(self, cid: int) -> None:
        """Lease return on disconnect — the fault-tolerance core (ref :80-84)."""
        returned = 0
        with self._lock:
            for u in self._assigned.pop(cid, ()):
                self._urls.put(u)
                returned += 1
            self._last_seen.pop(cid, None)
            self._conns.pop(cid, None)
        if returned:
            self._m_requeued.inc(returned)
            from advanced_scrapper_tpu.obs import trace

            trace.record(
                "event", "lease.requeue", client=cid, urls=returned
            )

    # -- heartbeat / TTL reclaim -------------------------------------------

    def _ttl_reaper(self) -> None:
        """Requeue leases whose client stopped producing complete frames
        for ``lease_ttl`` seconds — a wedged worker holds a perfectly
        healthy TCP connection, so disconnect-based reclaim (the only
        mechanism before the fleet PR) never fires for it.  Expiry also
        cuts the connection: late results from the zombie are then
        rejected by the assignment guard as strays."""
        ttl = self.cfg.lease_ttl
        tick = max(0.05, min(1.0, ttl / 4))
        while not self._stop.wait(tick):
            now = time.monotonic()
            expired: list[tuple[int, socket.socket | None]] = []
            with self._lock:
                for cid, seen in list(self._last_seen.items()):
                    if now - seen > ttl:
                        self._last_seen.pop(cid, None)
                        expired.append((cid, self._conns.pop(cid, None)))
            for cid, conn in expired:
                self._m_ttl_expired.inc()
                from advanced_scrapper_tpu.obs import trace

                trace.record("event", "lease.ttl_expired", client=cid)
                self._return_unprocessed(cid)
                if conn is not None:
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass

    def _handle_client(self, conn: socket.socket, cid: int) -> None:
        from advanced_scrapper_tpu.obs import trace as _trace

        reader = _LineReader(conn, max_line=self.cfg.max_frame_bytes)
        wlock = threading.Lock()
        try:
            while not self._stop.is_set():
                msg = reader.readline()
                if msg is None:
                    return
                with self._lock:
                    self._last_seen[cid] = time.monotonic()
                kind = msg.get("type")
                # propagated trace context (the client stamps its frames):
                # server-side lease spans stitch into the worker's trace
                tctx = _trace.context_from_wire(msg.pop("_trace", None))
                if kind == "heartbeat":
                    continue  # liveness only; the stamp above is the point
                if kind == "request_tasks":
                    self.stats.record_request()
                    adm = None
                    if self.admission is not None:
                        depth = None
                        if self.admission.max_queue > 0:
                            # only computed when a queue limit will read
                            # it: summing lens under the lock (no set
                            # copies) — the refill path is hot under
                            # exactly the load admission protects
                            with self._lock:
                                depth = sum(
                                    len(u) for u in self._assigned.values()
                                )
                        adm = self.admission.admit(queue_depth=depth)
                        if not adm.admitted:
                            # shed the GRANT, not the client: empty batch
                            # + retry-after, counted; the url queue keeps
                            # its work for whoever is admitted next
                            self._m_shed.inc()
                            _send_json(
                                conn, wlock,
                                {
                                    "type": "task_batch", "urls": [],
                                    "shed": True,
                                    "retry_after": adm.retry_after,
                                },
                            )
                            continue
                    try:
                        with _trace.trace_context(*(tctx or (None, None))):
                            with _trace.span("lease.lease", client=cid):
                                urls = self._lease(
                                    cid, int(msg.get("num_urls", 1))
                                )
                    finally:
                        if adm is not None:
                            self.admission.release(adm)
                    _send_json(conn, wlock, {"type": "task_batch", "urls": urls})
                elif kind == "result":
                    self.stats.record_response()
                    url = msg.get("url")
                    if tctx is not None:
                        _trace.record(
                            "event", "lease.result",
                            client=cid, url=msg.get("url"), trace=tctx[0],
                        )
                    with self._lock:
                        # accept only urls this client actually holds: a
                        # duplicate or stray result (a client racing its
                        # own half-frame death, a reconnect replay) must
                        # neither double-decrement the pending count (it
                        # would end the run with urls still queued) nor
                        # append a second row for a finished url
                        known = url in self._assigned.get(cid, ())
                        if known:
                            self._assigned[cid].discard(url)
                            self._pending -= 1
                    if known:
                        self._m_results.inc()
                        self.results.append(
                            {"url": url, "html_content": msg.get("html_content", "")}
                        )
                    else:
                        self._m_stray.inc()
                elif kind == "tasks_completed":
                    _send_json(conn, wlock, {"type": "acknowledge_completion"})
                    return
        except (ConnectionError, json.JSONDecodeError, OSError, FrameTooLong):
            pass  # FrameTooLong: counted in the reader; teardown requeues
        finally:
            self._return_unprocessed(cid)
            conn.close()

    # -- centralized parsing (ref server1.py:232-309) ----------------------

    def process_results(
        self,
        extractor: Callable,
        success_csv: str,
        failed_csv: str,
        *,
        on_success: Callable[[dict], None] | None = None,
    ) -> tuple[int, int]:
        """Parse every returned HTML with the extractor plugin → CSVs.

        ``ERROR:``-prefixed payloads (the client's fetch-failure sentinel)
        land in the failed CSV verbatim.
        """
        from advanced_scrapper_tpu.extractors import (
            FAILED_FIELDS,
            SUCCESS_FIELDS,
        )
        from advanced_scrapper_tpu.storage.csvio import AppendCsv

        ok = bad = 0
        with AppendCsv(success_csv, SUCCESS_FIELDS) as okc, AppendCsv(
            failed_csv, FAILED_FIELDS
        ) as badc:
            for r in self.results:
                url, html = r["url"], r["html_content"]
                if html.startswith("ERROR:"):
                    badc.write_row({"url": url, "error": html[len("ERROR:") :].strip()})
                    bad += 1
                    continue
                try:
                    data = extractor(BeautifulSoup(html, "html.parser"))
                except Exception as e:
                    badc.write_row({"url": url, "error": str(e)})
                    bad += 1
                    continue
                if not data.get("title"):
                    badc.write_row({"url": url, "error": "Title is empty"})
                    bad += 1
                    continue
                data["url"] = url
                okc.write_row(data)
                ok += 1
                if on_success is not None:
                    on_success(dict(data))
        return ok, bad


class LeaseClient:
    """Worker node: fetch threads fed by a leased local queue (client1.py)."""

    def __init__(
        self,
        cfg: FeedConfig,
        transport_factory: Callable[[], object],
        *,
        host: str | None = None,
        port: int | None = None,
        sleep=time.sleep,
        connect: Callable | None = None,
    ):
        self.cfg = cfg
        self.host = host if host is not None else cfg.host
        self.port = port if port is not None else cfg.port
        self.transport_factory = transport_factory
        self.sleep = sleep
        # injectable dialer (``(host, port) -> socket``): the seam the
        # chaos harness uses to put a ChaosSocket under the whole client
        # without touching protocol code (net/chaos.py)
        self._connect = connect
        # leased-work and result queues as runtime Edges (queue-compat
        # surface): fleet hops ride the same abstraction as local stages
        self._tasks: Edge = Edge("tasks", graph="lease_client")
        self._results: Edge = Edge("results", graph="lease_client")
        self._inflight = 0              # urls popped but not yet resulted
        self._inflight_lock = threading.Lock()
        self._stop = threading.Event()
        self._drained = threading.Event()  # server sent an empty batch
        self._shed_until = 0.0  # monotonic: no lease requests before this
        #   (the server shed our grant and told us when to come back)
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._threads: list[threading.Thread] = []

    def _connect_with_backoff(self) -> socket.socket:
        """Dial the server, retrying refused/injected connect failures
        with capped exponential backoff + deterministic jitter — a worker
        that boots a moment before its server (or behind a flaky link)
        must join the fleet, not die on the first ECONNREFUSED."""
        from advanced_scrapper_tpu.net.rpc import backoff_delays

        dial = self._connect or (
            lambda addr: socket.create_connection(addr, timeout=10)
        )
        attempts = max(1, self.cfg.connect_retries + 1)
        delays = backoff_delays(
            attempts - 1,
            base=self.cfg.connect_backoff,
            cap=2.0,
            seed=f"lease-connect|{self.host}:{self.port}",
        )
        last: Exception | None = None
        for attempt in range(attempts):
            if attempt:
                from advanced_scrapper_tpu.obs import telemetry

                telemetry.event_counter(
                    "astpu_lease_connect_retries_total",
                    "lease-client connect attempts beyond the first",
                ).inc()
                self.sleep(delays[attempt - 1])
            try:
                return dial((self.host, self.port))
            except OSError as e:
                last = e
        raise ConnectionError(
            f"lease server {self.host}:{self.port} unreachable after "
            f"{attempts} attempts: {last}"
        ) from last

    def run(self, *, max_seconds: float = 60.0) -> int:
        """Connect, pull leases, fetch, stream results; returns #fetched.

        Stops when the server's queue is drained (an empty ``task_batch``)
        and all local work is done, or after ``max_seconds``.
        """
        from advanced_scrapper_tpu.obs import trace as _trace

        # one trace per client run (inheriting an ambient one if the
        # caller opened it): every frame this worker sends is stamped, so
        # the server's lease/result spans stitch to THIS worker
        ctx = _trace.current_context()
        if ctx is None and _trace.enabled():
            ctx = (_trace.new_trace_id(), _trace.new_span_id())
        tfrag = {"t": ctx[0], "s": ctx[1]} if ctx else None

        def _stamp(obj: dict) -> dict:
            if tfrag is not None:
                obj["_trace"] = tfrag
            return obj

        self._sock = self._connect_with_backoff()
        reader = _LineReader(self._sock, max_line=self.cfg.max_frame_bytes)
        fetched = 0

        def receiver():
            nonlocal fetched
            try:
                while not self._stop.is_set():
                    msg = reader.readline()
                    if msg is None:
                        return
                    if msg.get("type") == "task_batch":
                        urls = msg.get("urls", [])
                        if msg.get("shed"):
                            # an overload shed, NOT a drained queue: honor
                            # the retry-after before the next request (a
                            # shed misread as drained would end the run
                            # with work still queued)
                            self._shed_until = time.monotonic() + float(
                                msg.get("retry_after", 0.0)
                            )
                            from advanced_scrapper_tpu.obs import telemetry

                            telemetry.event_counter(
                                "astpu_lease_shed_honored_total",
                                "shed lease grants whose retry-after the "
                                "client honored",
                            ).inc()
                        elif not urls:
                            self._drained.set()
                        for u in urls:
                            self._tasks.put(u)
                    elif msg.get("type") == "acknowledge_completion":
                        return
            except (
                ConnectionError, OSError, json.JSONDecodeError, FrameTooLong
            ):
                return

        def worker():
            transport = self.transport_factory()
            try:
                while not self._stop.is_set():
                    try:
                        url = self._tasks.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    with self._inflight_lock:
                        self._inflight += 1
                    try:
                        html = transport.fetch(url)
                    except Exception as e:
                        html = f"ERROR: {e}"
                    finally:
                        self._results.put((url, html))
                        with self._inflight_lock:
                            self._inflight -= 1
            finally:
                try:
                    transport.close()
                except Exception:
                    pass

        def sender():
            nonlocal fetched
            while not (self._stop.is_set() and self._results.empty()):
                try:
                    url, html = self._results.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    _send_json(
                        self._sock,
                        self._wlock,
                        _stamp(
                            {"type": "result", "url": url, "html_content": html}
                        ),
                    )
                    fetched += 1
                except (ConnectionError, OSError):
                    return

        threads = [threading.Thread(target=receiver, daemon=True)]
        threads += [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.cfg.client_threads)
        ]
        threads.append(threading.Thread(target=sender, daemon=True))
        for t in threads:
            t.start()
        self._threads = threads

        # monitor loop: low-water refill, rate-capped (client1.py:209-234)
        interval = 1.0 / self.cfg.client_rate
        hb_interval = self.cfg.heartbeat_interval or (
            min(1.0, self.cfg.lease_ttl / 4) if self.cfg.lease_ttl > 0 else 0
        )
        last_frame = time.monotonic()
        deadline = time.monotonic() + max_seconds
        try:
            while time.monotonic() < deadline:
                with self._inflight_lock:
                    inflight = self._inflight
                if (
                    self._drained.is_set()
                    and self._tasks.empty()
                    and self._results.empty()
                    and inflight == 0
                ):
                    break
                if (
                    self._tasks.qsize() < self.cfg.min_queue_length
                    and time.monotonic() >= self._shed_until
                ):
                    try:
                        _send_json(
                            self._sock,
                            self._wlock,
                            _stamp(
                                {
                                    "type": "request_tasks",
                                    "num_urls": self.cfg.batch_size,
                                }
                            ),
                        )
                        last_frame = time.monotonic()
                    except (ConnectionError, OSError):
                        break
                elif (
                    hb_interval
                    and time.monotonic() - last_frame >= hb_interval
                ):
                    # liveness while busy: a full local queue means no
                    # request frames, and slow fetches mean no result
                    # frames — without this the server's TTL reaper
                    # would reclaim leases we are actively working
                    try:
                        _send_json(
                            self._sock, self._wlock, {"type": "heartbeat"}
                        )
                        last_frame = time.monotonic()
                    except (ConnectionError, OSError):
                        break
                self.sleep(interval)
            # graceful completion handshake
            try:
                _send_json(self._sock, self._wlock, {"type": "tasks_completed"})
            except (ConnectionError, OSError):
                pass
            self.sleep(0.1)
        finally:
            self._stop.set()
            for t in threads:
                t.join(timeout=2)
            self._sock.close()
        return fetched
