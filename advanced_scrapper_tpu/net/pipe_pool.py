"""Subprocess worker-pool orchestrator (stdin/stdout pipes).

Re-implements ``experiental/06_worker.py:14-71``: N ``pipe_worker``
subprocesses launched with their config as a JSON argv blob; the dispatcher
writes a URL line to an idle worker's stdin, per-worker reader threads
collect JSON result lines from stdout and JSON errors from stderr, and
busy-state bookkeeping frees a worker as soon as its line arrives.
"""

from __future__ import annotations

import json
import queue
import subprocess
import sys
import threading
import time


class PipePool:
    def __init__(self, num_workers: int = 3, config: dict | None = None):
        # ref 06_worker.py:14 NUM_WORKERS=3
        self.num_workers = num_workers
        self.config = config or {}
        self._procs: list[subprocess.Popen] = []
        self._busy: list[bool] = []
        self._lock = threading.Lock()
        self._free = threading.Semaphore(0)
        self.results: "queue.Queue[dict]" = queue.Queue()
        self.errors: "queue.Queue[dict]" = queue.Queue()
        self._threads: list[threading.Thread] = []

    def start(self) -> "PipePool":
        blob = json.dumps(self.config)
        for i in range(self.num_workers):
            p = subprocess.Popen(
                [sys.executable, "-m", "advanced_scrapper_tpu.net.pipe_worker", blob],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                bufsize=1,
            )
            self._procs.append(p)
            self._busy.append(False)
            self._free.release()
            for stream, sink in ((p.stdout, self.results), (p.stderr, self.errors)):
                t = threading.Thread(
                    target=self._reader, args=(i, stream, sink), daemon=True
                )
                t.start()
                self._threads.append(t)
        return self

    def _reader(self, idx: int, stream, sink: "queue.Queue[dict]") -> None:
        for line in stream:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue  # stray prints from libraries
            sink.put(obj)
            with self._lock:
                if self._busy[idx]:
                    self._busy[idx] = False
                    self._free.release()
        # Stream EOF: the worker died (or stop() closed it).  Only the stdout
        # reader reclaims the slot — doing it from both readers would release
        # the semaphore twice.  A worker that crashed mid-task surfaces as an
        # error result so drain() callers are not left one item short.
        if sink is self.results:
            with self._lock:
                if self._busy[idx]:
                    self._busy[idx] = False
                    self._free.release()
                    self.errors.put(
                        {"error": "worker exited mid-task", "worker": idx}
                    )

    def dispatch(self, url: str, timeout: float = 60.0) -> bool:
        """Hand one URL to an idle worker (blocks for one to free up)."""
        if not self._free.acquire(timeout=timeout):
            return False
        with self._lock:
            for i, p in enumerate(self._procs):
                if not self._busy[i] and p.poll() is None:
                    self._busy[i] = True
                    try:
                        p.stdin.write(url + "\n")
                        p.stdin.flush()
                        return True
                    except (BrokenPipeError, OSError):
                        self._busy[i] = False
        self._free.release()
        return False

    def drain(self, n: int, timeout: float = 60.0) -> list[dict]:
        """Collect n results/errors (interleaved as they arrive)."""
        out: list[dict] = []
        deadline = time.monotonic() + timeout
        while len(out) < n and time.monotonic() < deadline:
            got = False
            for q in (self.results, self.errors):
                if len(out) >= n:
                    break
                try:
                    out.append(q.get(timeout=0.05))
                    got = True
                except queue.Empty:
                    pass
            if not got:
                time.sleep(0.02)
        return out

    def stop(self) -> None:
        for p in self._procs:
            try:
                p.stdin.close()
            except Exception:
                pass
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
