"""Fetch transports — the CPU-side fetch substrate behind the engine.

The reference drives external browser binaries over WebDriver
(geckodriver/Firefox at ``constant_rate_scrapper.py:136-139``,
undetected-chromedriver in ``experiental/00_worker.py:31``); the north star
keeps fetching CPU-side.  The engine only needs ``fetch(url) -> html``, so
transports are swappable:

- :class:`SeleniumTransport` — headless Firefox with the reference's
  preferences (images off, JS off, 30 s page-load timeout, readyState wait);
  available only where selenium + geckodriver exist.
- :class:`StealthChromeTransport` — anti-bot Chrome via
  undetected-chromedriver (the reference's experimental fleet substrate,
  ``experiental/00_worker.py:2,31-33``); explicit opt-in, never auto-picked.
- :class:`RequestsTransport` — plain HTTP with a browser UA (the substrate
  of ``ticker_symbol_query*.py``).
- :class:`MockTransport` — fixture pages for tests and offline runs.

``FetchError`` carries the error string; the engine fingerprints it for
rate-limit detection exactly like the reference fingerprints WebDriver
exceptions (``constant_rate_scrapper.py:190-193``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Mapping

USER_AGENT = (
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 "
    "(KHTML, like Gecko) Chrome/120.0.0.0 Safari/537.36"
)


class FetchError(Exception):
    """Fetch failure; ``str(e)`` is the error string recorded in failed CSVs."""


class MockTransport:
    """Serve canned pages.  ``pages`` maps url → html | Exception | callable;
    unknown urls raise FetchError("about:neterror")."""

    def __init__(self, pages: Mapping[str, object] | Callable[[str], str], latency: float = 0.0):
        self._pages = pages
        self._latency = latency
        self.fetched: list[str] = []

    def fetch(self, url: str) -> str:
        if self._latency:
            time.sleep(self._latency)
        self.fetched.append(url)
        if callable(self._pages):
            return self._pages(url)
        page = self._pages.get(url)
        if page is None:
            # deliberately NOT 'about:neterror': that substring is the
            # engine's rate-limit fingerprint and would trip a long global
            # pause for every missing fixture
            raise FetchError(f"no fixture for {url}")
        if isinstance(page, Exception):
            raise page
        if callable(page):
            return page(url)
        return str(page)

    def close(self) -> None:
        pass


class ChaosTransport:
    """Deliberate fault injection around any inner transport.

    The reference has **no** fault injection anywhere (SURVEY.md §5.3) —
    its failure handling is only ever exercised by real outages.  This
    wrapper makes the failure paths testable on demand: seeded, reproducible
    injection of fetch errors, rate-limit fingerprints (the
    ``about:neterror`` string the engine's circuit breaker keys on, ref
    ``constant_rate_scrapper.py:190-193``), rate-limit sentinel *pages*
    (the extractor-detected flavour, ref ``extractors/yfin.py:18-21``),
    and latency spikes.  Fault assignment is a pure function of
    ``(seed, url)`` — NOT a shared random stream — so injection is
    reproducible even when the engine fetches from many worker threads in
    nondeterministic order (a given url faults identically on every run
    and every retry with the same seed).  A url that faults is not retried
    here — failure capture, resume and the pause circuit downstream are
    exactly what is under test.
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        neterror_rate: float = 0.0,
        rate_limit_page_rate: float = 0.0,
        latency_spike: tuple[float, float] = (0.0, 0.0),
        rate_limit_page: str | None = None,
    ):
        import threading

        self._inner = inner
        self._seed = seed
        self._error_rate = error_rate
        self._neterror_rate = neterror_rate
        self._rl_page_rate = rate_limit_page_rate
        self._spike_rate, self._spike_secs = latency_spike
        if rate_limit_page is None:
            # build the default page from the extractor's own sentinel
            # phrases so injection keeps tripping detection if they change
            from advanced_scrapper_tpu.extractors.yfin import _RATE_LIMIT_NEEDLES

            rate_limit_page = (
                "<html><body>"
                + "".join(f"<p>{needle}</p>" for needle in _RATE_LIMIT_NEEDLES)
                + "</body></html>"
            )
        self._rl_page = rate_limit_page
        # engine workers share one transport: counter updates must not race
        self._count_lock = threading.Lock()
        self.injected: dict[str, int] = {
            "error": 0, "neterror": 0, "rate_limit_page": 0, "spike": 0
        }

    def _count(self, kind: str) -> None:
        with self._count_lock:
            self.injected[kind] += 1

    def fetch(self, url: str) -> str:
        import random

        # seeding Random with a string hashes its bytes (sha512) — stable
        # across processes and threads, unlike the builtin str hash
        r = random.Random(f"{self._seed}|{url}").random
        if self._spike_rate and r() < self._spike_rate:
            self._count("spike")
            time.sleep(self._spike_secs)
        if self._error_rate and r() < self._error_rate:
            self._count("error")
            raise FetchError(f"injected fault for {url}")
        if self._neterror_rate and r() < self._neterror_rate:
            self._count("neterror")
            raise FetchError(f"about:neterror (injected) for {url}")
        if self._rl_page_rate and r() < self._rl_page_rate:
            self._count("rate_limit_page")
            return self._rl_page
        return self._inner.fetch(url)

    def close(self) -> None:
        self._inner.close()


class RequestsTransport:
    def __init__(self, timeout: float = 30.0, user_agent: str = USER_AGENT):
        import requests

        self._session = requests.Session()
        self._session.headers["User-Agent"] = user_agent
        self._timeout = timeout

    def fetch(self, url: str) -> str:
        import requests

        try:
            resp = self._session.get(url, timeout=self._timeout)
        except requests.RequestException as e:
            raise FetchError(str(e)) from e
        if resp.status_code >= 400:
            raise FetchError(f"HTTP {resp.status_code} for {url}")
        return resp.text

    def close(self) -> None:
        self._session.close()


class _WebDriverTransport:
    """Shared WebDriver fetch contract: navigation + readyState wait,
    scroll-until-stable, error wrapping, quit.  Subclasses provide
    ``self._driver`` and ``self._ready_timeout`` in ``__init__``."""

    _driver = None
    _ready_timeout: float = 10.0

    def fetch(self, url: str) -> str:
        try:
            self._driver.get(url)
            # readyState poll — selenium's WebDriverWait semantics (0.5 s
            # poll, TimeoutException after the budget) implemented locally
            # so the same code drives selenium drivers AND the stdlib wire
            # client (net/webdriver.py), which has no selenium to import
            deadline = time.monotonic() + self._ready_timeout
            while (
                self._driver.execute_script("return document.readyState")
                != "complete"
            ):
                if time.monotonic() >= deadline:
                    raise FetchError(
                        f"timeout waiting for readyState complete on {url}"
                    )
                time.sleep(0.5)
            return self._driver.page_source
        except FetchError:
            raise
        except Exception as e:  # WebDriver raises many exception types
            raise FetchError(str(e)) from e

    def fetch_scrolled(
        self,
        url: str,
        *,
        max_scrolls: int = 10,
        settle_s: float = 2.0,
        sleep: Callable[[float], None] = time.sleep,
    ) -> str:
        """Fetch, then scroll to the bottom until the page height stabilises
        (lazy-loaded feeds; ref ``experiental/04_crypto_1.py:57-63``).

        ``max_scrolls`` bounds infinite feeds; ``settle_s`` is the ref's
        post-scroll wait for the lazy loader to append content.
        """
        self.fetch(url)  # navigation + readyState wait
        try:
            last_height = self._driver.execute_script(
                "return document.body.scrollHeight"
            )
            for _ in range(max_scrolls):
                self._driver.execute_script(
                    "window.scrollTo(0, document.body.scrollHeight);"
                )
                sleep(settle_s)
                height = self._driver.execute_script(
                    "return document.body.scrollHeight"
                )
                if height == last_height:
                    break  # stable: nothing more is lazy-loading
                last_height = height
            return self._driver.page_source
        except Exception as e:
            raise FetchError(str(e)) from e

    def close(self) -> None:
        self._driver.quit()


class SeleniumTransport(_WebDriverTransport):
    """Headless Firefox via geckodriver, reference preferences
    (``constant_rate_scrapper.py:33-41,136-153``)."""

    def __init__(
        self,
        page_load_timeout: float = 30.0,
        ready_state_timeout: float = 10.0,
        executable_path: str = "geckodriver",
    ):
        from selenium import webdriver
        from selenium.webdriver.firefox.options import Options
        from selenium.webdriver.firefox.service import Service

        options = Options()
        options.set_preference("permissions.default.image", 2)
        options.set_preference("javascript.enabled", False)
        options.set_preference("dom.ipc.plugins.enabled.libflashplayer.so", False)
        options.add_argument("-headless")
        self._driver = webdriver.Firefox(
            service=Service(executable_path=executable_path), options=options
        )
        self._driver.set_page_load_timeout(page_load_timeout)
        self._ready_timeout = ready_state_timeout


class StealthChromeTransport(_WebDriverTransport):
    """Anti-bot Chrome via undetected-chromedriver — the reference's
    experimental fleet substrate (``experiental/00_worker.py:2,31-33``,
    ``03_worker_multi.py:64``), which patches Chrome to evade
    navigator.webdriver/CDP fingerprinting where stock Firefox is blocked.

    Same ``fetch()`` contract as every other transport, so engines and
    pools are substrate-agnostic; select with
    ``ScraperConfig.transport = "stealth-chrome"``.  The import is lazy and
    optional — without the package this raises ImportError at construction
    (``make_transport("auto")`` never picks it implicitly; anti-bot
    crawling should be an explicit operator choice).
    """

    #: uc.Chrome() runs a binary patcher over a shared cached chromedriver;
    #: concurrent instantiation (engine workers each build their transport)
    #: can collide in the patcher — construction is serialized process-wide.
    _construct_lock = threading.Lock()

    def __init__(
        self,
        page_load_timeout: float = 30.0,
        ready_state_timeout: float = 10.0,
        headless: bool = True,
        options=None,
    ):
        import undetected_chromedriver as uc

        if options is None:
            options = uc.ChromeOptions()
            if headless:
                options.add_argument("--headless=new")
        with StealthChromeTransport._construct_lock:
            self._driver = uc.Chrome(options=options)
        self._driver.set_page_load_timeout(page_load_timeout)
        self._ready_timeout = ready_state_timeout


class WireFirefoxTransport(_WebDriverTransport):
    """Headless Firefox via geckodriver over the FIRST-PARTY WebDriver wire
    client (``net/webdriver.py``) — no selenium package needed.  Same
    reference preferences and fetch contract as :class:`SeleniumTransport`;
    ``remote_url`` attaches to an already-running driver (or grid/test
    endpoint) instead of spawning geckodriver."""

    def __init__(
        self,
        page_load_timeout: float = 30.0,
        ready_state_timeout: float = 10.0,
        executable_path: str = "geckodriver",
        remote_url: str | None = None,
    ):
        from advanced_scrapper_tpu.net.webdriver import WireFirefoxDriver

        self._driver = WireFirefoxDriver(
            executable_path, remote_url=remote_url
        )
        self._driver.set_page_load_timeout(page_load_timeout)
        self._ready_timeout = ready_state_timeout


class WireChromeTransport(_WebDriverTransport):
    """Headless plain Chrome via chromedriver over the wire client —
    explicit opt-in (``--transport chrome-wire``), like every Chrome
    substrate here; for anti-bot crawling use stealth-chrome instead."""

    def __init__(
        self,
        page_load_timeout: float = 30.0,
        ready_state_timeout: float = 10.0,
        executable_path: str = "chromedriver",
        remote_url: str | None = None,
    ):
        from advanced_scrapper_tpu.net.webdriver import WireChromeDriver

        self._driver = WireChromeDriver(
            executable_path, remote_url=remote_url
        )
        self._driver.set_page_load_timeout(page_load_timeout)
        self._ready_timeout = ready_state_timeout


def stealth_chrome_available() -> bool:
    """True when the undetected-chromedriver package is importable."""
    try:
        import undetected_chromedriver  # noqa: F401
    except ImportError:
        return False
    return True


def _resolve_binary(name: str) -> str | None:
    """PATH hit, else a cwd-relative file made absolute (the reference
    ships geckodriver next to the scripts, ``.MISSING_LARGE_BLOBS:1-2``)
    — ``Popen`` resolves bare names through PATH only, so a cwd hit must
    be returned as an absolute path for the spawn to agree with us."""
    import shutil

    hit = shutil.which(name)
    if hit is not None:
        return hit
    if os.path.isfile(name) and os.access(name, os.X_OK):
        return os.path.abspath(name)
    return None


def selenium_available() -> bool:
    """True only when the whole stack exists: the selenium package AND a
    geckodriver binary (the external WebDriver shim the reference ships,
    ``.MISSING_LARGE_BLOBS:1-2``)."""
    try:
        import selenium  # noqa: F401
    except ImportError:
        return False
    return _resolve_binary("geckodriver") is not None


def geckodriver_available() -> bool:
    """True when a geckodriver binary exists — all the wire transport
    needs (the selenium package is optional with ``net/webdriver.py``)."""
    return _resolve_binary("geckodriver") is not None


def make_transport(
    name: str = "auto",
    *,
    page_load_timeout: float = 30.0,
    ready_state_timeout: float = 10.0,
    pages=None,
    **kw,
):
    """``auto`` prefers browser fidelity and falls back to HTTP: selenium
    if the package is installed, else the first-party wire client if a
    geckodriver binary exists, else plain requests.

    Timeouts map onto whichever transport is chosen: browser transports get
    both, requests uses ``page_load_timeout`` as its request timeout.
    """
    if name == "auto":
        if selenium_available():
            try:
                return SeleniumTransport(
                    page_load_timeout=page_load_timeout,
                    ready_state_timeout=ready_state_timeout,
                    **kw,
                )
            except Exception:
                pass  # broken browser stack → HTTP fallback, as documented
        # fall-through, not elif: a selenium install that imports but fails
        # to construct must still try the wire client before degrading to
        # plain HTTP — the geckodriver binary is all the wire path needs
        gecko = _resolve_binary("geckodriver")
        if gecko is not None:
            try:
                return WireFirefoxTransport(
                    page_load_timeout=page_load_timeout,
                    ready_state_timeout=ready_state_timeout,
                    **{"executable_path": gecko, **kw},
                )
            except Exception:
                pass
        name = "requests"
    if name == "selenium":
        return SeleniumTransport(
            page_load_timeout=page_load_timeout,
            ready_state_timeout=ready_state_timeout,
            **kw,
        )
    if name == "firefox-wire":
        return WireFirefoxTransport(
            page_load_timeout=page_load_timeout,
            ready_state_timeout=ready_state_timeout,
            **kw,
        )
    if name == "chrome-wire":
        return WireChromeTransport(
            page_load_timeout=page_load_timeout,
            ready_state_timeout=ready_state_timeout,
            **kw,
        )
    if name == "stealth-chrome":
        return StealthChromeTransport(
            page_load_timeout=page_load_timeout,
            ready_state_timeout=ready_state_timeout,
            **kw,
        )
    if name == "requests":
        return RequestsTransport(timeout=page_load_timeout)
    if name == "mock":
        return MockTransport(pages if pages is not None else {})
    raise ValueError(f"unknown transport '{name}'")
