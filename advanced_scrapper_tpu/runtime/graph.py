"""Stage-graph scheduler: edges, stages, and the graph that runs them.

Design notes (the four questions every async layer used to answer its own
way, answered once here):

**How do items move.**  A :class:`Edge` is a bounded FIFO owned by the
graph.  Producers ``put`` (blocking while full — backpressure is the
default, not an option), consumers ``pop`` one item or ``pop_batch`` with
the full-tile ``min_fill`` discipline (wait for k items unless a timeout,
a close, or a producer's rejected push says "dispatch what you have" —
the ``pipeline/feed.py`` staging rules, generalised).  Edges also speak
the ``queue.Queue`` surface (``get``/``put``/``task_done``/``qsize``) so
pre-runtime worker bodies (the elastic scraper pool) ride them unchanged.

**What bounds them.**  Capacity, declared per edge.  ``min_fill`` is
clamped to capacity so a consumer can never wait for more items than the
edge may hold (the feed deadlock rule).

**Who wakes whom.**  Closes are one-way and wake every waiter.  An edge
auto-closes when its LAST producer stage exits, so drains propagate in
topological order with no bespoke sentinel protocols.  A producer whose
timed put is rejected wakes ``min_fill`` waiters (partial tiles beat
starvation under backpressure).  A failing worker fails the whole graph:
every edge closes, every blocked peer wakes, and :meth:`StageGraph.join`
re-raises the first error — no stranded consumers, no half-alive fleets.

**What the crash sees.**  Every live graph registers with the
``obs/trace`` flight recorder: on a chaos fault (``fsio._die``) or crash
dump, :func:`snapshot_all` records each graph's per-stage in-flight items
and per-edge depths BEFORE the process dies, so the sweep harness can
assert on what the scheduler held at the kill point.

Telemetry (no-op handles when disabled): per-edge depth callback gauges,
items-in/out counters and put/get stall-seconds counters; per-stage item
throughput counters and busy-seconds counters — the whole graph is
observable without any stage writing a metric itself.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

__all__ = [
    "DONE",
    "RETRY",
    "Edge",
    "EdgeClosed",
    "FanoutPool",
    "Stage",
    "StageGraph",
    "live_graphs",
    "snapshot_all",
]


class _Sentinel:
    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<runtime.{self._name}>"


#: returned by :meth:`Edge.pop` (and accepted from stage sources) when the
#: stream is exhausted: closed and drained, no more items will ever come.
DONE = _Sentinel("DONE")

#: returned by a stage source (or :meth:`Edge.pop` on timeout) meaning
#: "nothing yet — poll again".
RETRY = _Sentinel("RETRY")


class EdgeClosed(RuntimeError):
    """Raised by :meth:`Edge.put_nowait` on a closed edge (the blocking
    :meth:`Edge.put` returns False instead — stage loops branch, callers
    on the queue-compat surface get the loud version)."""


class Edge:
    """Named bounded FIFO between stages; the runtime owns the locking,
    backpressure, close propagation and telemetry.

    Thread-safe.  ``capacity=None`` means unbounded (for pre-filled work
    lists); bounded edges block producers when full.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        name: str,
        capacity: int | None = None,
        *,
        graph: str = "-",
        instance: str | None = None,
    ):
        self.name = name
        self.graph = graph
        # graph-owned edges inherit the graph's instance label; bare edges
        # (lease queues, FanoutPool task queues) draw their own — two live
        # LeaseClients must never replace each other's gauge series or
        # co-mingle counters (the PR-3 per-instance-series invariant)
        self._graph_owned = instance is not None
        if instance is None:
            with Edge._seq_lock:
                instance = f"e{Edge._seq}"
                Edge._seq += 1
        self.capacity = capacity if capacity and capacity > 0 else None
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._rejects = 0  # timed-out puts; wakes min_fill waiters
        self._producers = 0
        self._in = 0
        self._out = 0
        self._instrument(graph, instance)
        if not self._graph_owned:
            # bare edges join the crash-snapshot registry themselves —
            # the lease plane's backlog must show up in a fault dump
            # exactly like a graph-owned edge's
            with _live_lock:
                _BARE_EDGES.add(self)

    # -- telemetry ---------------------------------------------------------

    def _instrument(self, graph: str, instance: str) -> None:
        """Counters are keyed by (graph, edge) WITHOUT the instance label
        — graphs are built per call (a 'dedup.h2d' per dedup_reps, a
        'scrape' per run), and per-instance counter series would leak in
        the registry forever; cumulative-across-instances is the PR-3
        feed-counter pattern.  Gauges DO carry the instance label (two
        live lease clients must not replace each other's depth series)
        and are weakref-swept with the edge, so they never accumulate."""
        from advanced_scrapper_tpu.obs import telemetry

        labels = {"graph": graph, "edge": self.name}
        self._m_in = telemetry.counter(
            "astpu_edge_items_total", "items accepted by the edge",
            dir="in", **labels,
        )
        self._m_out = telemetry.counter(
            "astpu_edge_items_total", "items handed to consumers",
            dir="out", **labels,
        )
        self._m_stall_put = telemetry.counter(
            "astpu_edge_stall_seconds_total",
            "seconds producers spent blocked on a full edge",
            side="put", **labels,
        )
        self._m_stall_get = telemetry.counter(
            "astpu_edge_stall_seconds_total",
            "seconds consumers spent waiting on an empty edge",
            side="get", **labels,
        )
        telemetry.gauge_fn(
            "astpu_edge_depth",
            lambda e: len(e._items),
            owner=self,
            help="items buffered on the edge",
            g=instance,
            **labels,
        )
        telemetry.gauge_fn(
            "astpu_edge_capacity",
            lambda e: e.capacity or 0,
            owner=self,
            help="edge capacity (0 = unbounded)",
            g=instance,
            **labels,
        )

    # -- producer side -----------------------------------------------------

    def register_producer(self) -> "Edge":
        """Count an (external or stage) producer; the edge closes when the
        count, once positive, returns to zero."""
        with self._lock:
            self._producers += 1
        return self

    def producer_done(self) -> None:
        with self._lock:
            self._producers -= 1
            if self._producers <= 0 and not self._closed:
                self._close_locked()

    def put(self, item, timeout: float | None = None) -> bool:
        """Append ``item``; blocks while full.  Returns False (and wakes
        ``min_fill`` waiters — the rejection-wakeup rule) when the edge is
        closed or the timeout expires without space."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._closed:
                    self._rejects += 1
                    self._not_empty.notify_all()
                    return False
                if self.capacity is None or len(self._items) < self.capacity:
                    self._items.append(item)
                    self._in += 1
                    self._m_in.inc()
                    self._not_empty.notify()
                    return True
                t0 = time.perf_counter()
                if deadline is None:
                    self._not_full.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._not_full.wait(remaining):
                        self._m_stall_put.inc(time.perf_counter() - t0)
                        self._rejects += 1
                        self._not_empty.notify_all()
                        return False
                self._m_stall_put.inc(time.perf_counter() - t0)

    # -- consumer side -----------------------------------------------------

    def pop(self, timeout: float | None = None):
        """One item, else :data:`DONE` (closed and drained) or
        :data:`RETRY` (timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._items:
                    return self._pop_locked()
                if self._closed:
                    return DONE
                t0 = time.perf_counter()
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._not_empty.wait(remaining):
                        self._m_stall_get.inc(time.perf_counter() - t0)
                        return RETRY
                self._m_stall_get.inc(time.perf_counter() - t0)

    def pop_batch(
        self,
        max_n: int,
        *,
        min_fill: int = 1,
        timeout: float | None = None,
    ) -> list:
        """Up to ``max_n`` items, waiting for at least ``min_fill`` of them
        (clamped to capacity — the feed's no-deadlock rule) unless a close,
        a timeout, or a producer's rejected push ends the wait first.
        Returns a possibly-empty list; emptiness + :meth:`closed` + empty
        depth together mean exhausted."""
        if self.capacity is not None:
            min_fill = min(min_fill, self.capacity)
        min_fill = max(1, min(min_fill, max_n))
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            rejects_seen = self._rejects
            while (
                len(self._items) < min_fill
                and not self._closed
                and self._rejects == rejects_seen
            ):
                t0 = time.perf_counter()
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._not_empty.wait(remaining):
                        self._m_stall_get.inc(time.perf_counter() - t0)
                        break
                self._m_stall_get.inc(time.perf_counter() - t0)
            out = []
            while self._items and len(out) < max_n:
                out.append(self._pop_locked())
            return out

    def _pop_locked(self):
        item = self._items.popleft()
        self._out += 1
        self._m_out.inc()
        self._not_full.notify()
        return item

    def __iter__(self) -> Iterator:
        while True:
            item = self.pop()
            if item is DONE:
                return
            yield item

    # -- queue.Queue compatibility (elastic worker bodies) -----------------

    def get(self, block: bool = True, timeout: float | None = None):
        """``queue.Queue.get``: raises ``queue.Empty`` on timeout AND on a
        closed-and-drained edge (callers on this surface carry their own
        stop conditions)."""
        item = self.pop(timeout=timeout if block else 0.0)
        if item is DONE or item is RETRY:
            raise _queue.Empty
        return item

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait(self, item) -> None:
        if not self.put(item, timeout=0.0):
            raise _queue.Full if self._closed is False else EdgeClosed(
                f"edge '{self.name}' is closed"
            )

    def task_done(self) -> None:  # the runtime tracks drain via close/DONE
        pass

    def qsize(self) -> int:
        with self._lock:
            return len(self._items)

    def empty(self) -> bool:
        return self.qsize() == 0

    def __len__(self) -> int:
        return self.qsize()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """One-way: no further puts accepted; pops drain the remainder then
        report :data:`DONE`.  Wakes every waiter."""
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        self._closed = True
        self._not_empty.notify_all()
        self._not_full.notify_all()

    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "edge": self.name,
                "depth": len(self._items),
                "capacity": self.capacity or 0,
                "closed": self._closed,
                "in": self._in,
                "out": self._out,
            }
            if not self._graph_owned:
                snap["graph"] = self.graph
            return snap


@dataclass
class Stage:
    """Declarative stage spec; the graph owns its threads and queues.

    Exactly one of ``source`` (a zero-arg puller returning an item,
    :data:`RETRY`, or :data:`DONE` — shared by all workers, so it must be
    thread-safe), ``source_iter`` (any iterable; the graph wraps it in a
    locked puller, so a plain generator feeds a multi-worker stage
    safely — the pattern every encode-generator call site used to
    hand-roll), or ``in_edge`` feeds the stage.  ``fn`` transforms one
    item; ``None`` results are filtered, and with ``fan_out=True`` an
    iterable result emits item-by-item.  ``worker_init``/``worker_close``
    bracket per-worker context (a transport, a device handle); when
    ``worker_init`` is set, ``fn`` is called as ``fn(item, ctx)``.
    ``pausable`` stages honour the graph's :class:`~.pause.PauseGate`
    between pop and work.  ``tag(item)`` (optional) names the trace-span
    fields so corpus trace ids propagate across edges for free.
    """

    name: str
    fn: Callable | None = None
    in_edge: Edge | None = None
    out_edge: Edge | None = None
    source: Callable | None = None
    source_iter: Iterable | None = None
    workers: int = 1
    worker_init: Callable | None = None
    worker_close: Callable | None = None
    pausable: bool = False
    fan_out: bool = False
    tag: Callable | None = None
    # -- runtime state (owned by the graph) --
    live: int = field(default=0, repr=False)
    threads: list = field(default_factory=list, repr=False)


class StageGraph:
    """A set of stages wired by edges, run by one scheduler.

    Lifecycle: declare edges (:meth:`edge`) and stages (:meth:`stage`),
    :meth:`start`, optionally push into externally-produced edges (close
    them when done), consume a terminal edge (iterate it), then
    :meth:`join` — which re-raises the first worker error.  :meth:`stop`
    aborts: closes every edge and joins without draining.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(self, name: str, *, pause=None):
        self.name = name
        self.pause = pause  # a PauseGate (or None)
        with StageGraph._seq_lock:
            self._instance = str(StageGraph._seq)
            StageGraph._seq += 1
        self._edges: dict[str, Edge] = {}
        self._stages: dict[str, Stage] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._started = False
        self._in_flight: dict[tuple[str, int], str] = {}
        self._instrument()

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._m_items: dict[str, object] = {}
        self._m_busy: dict[str, object] = {}
        self._telemetry = telemetry

    def _stage_metrics(self, name: str):
        m = self._m_items.get(name)
        if m is None:
            # (graph, stage)-keyed, no instance label: same no-leak rule
            # as the edge counters (graphs are created per call)
            labels = {"graph": self.name, "stage": name}
            m = self._telemetry.counter(
                "astpu_stage_items_total", "items processed by the stage",
                **labels,
            )
            self._m_items[name] = m
            self._m_busy[name] = self._telemetry.counter(
                "astpu_stage_busy_seconds_total",
                "seconds the stage spent inside its fn",
                **labels,
            )
        return m, self._m_busy[name]

    # -- construction ------------------------------------------------------

    def edge(self, name: str, capacity: int | None = None) -> Edge:
        """Declare (or fetch) the named edge."""
        e = self._edges.get(name)
        if e is None:
            e = Edge(
                name, capacity, graph=self.name, instance=self._instance
            )
            self._edges[name] = e
        return e

    def stage(self, name: str, **kw) -> Stage:
        """Declare a stage (see :class:`Stage` for the spec fields)."""
        if self._started:
            raise RuntimeError("cannot add stages to a started graph")
        st = Stage(name=name, **kw)
        if st.source_iter is not None:
            if st.source is not None:
                raise ValueError(
                    f"stage '{name}' cannot have both source and source_iter"
                )
            st.source = _locked_iter_source(st.source_iter)
        if st.source is None and st.in_edge is None:
            raise ValueError(f"stage '{name}' needs a source or an in_edge")
        if st.source is not None and st.in_edge is not None:
            raise ValueError(f"stage '{name}' cannot have both source and in_edge")
        self._stages[name] = st
        if st.out_edge is not None:
            st.out_edge.register_producer()
        return st

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StageGraph":
        if self._started:
            return self
        self._started = True
        _register_graph(self)
        for st in self._stages.values():
            st.live = st.workers
            for w in range(st.workers):
                t = threading.Thread(
                    target=self._run_worker,
                    args=(st, w),
                    name=f"astpu-{self.name}-{st.name}-{w}",
                    daemon=True,
                )
                st.threads.append(t)
                t.start()
        return self

    @property
    def error(self) -> BaseException | None:
        return self._error

    def fail(self, exc: BaseException) -> None:
        """First error wins; every edge closes so no peer stays blocked."""
        with self._lock:
            if self._error is None:
                self._error = exc
        self._stop.set()
        for e in self._edges.values():
            e.close()

    def stop(self) -> None:
        """Abort: wake and stop every worker without draining."""
        self._stop.set()
        for e in self._edges.values():
            e.close()

    def join(self, timeout: float | None = None, *, raise_error: bool = True):
        """Wait for every worker (``timeout`` bounds the TOTAL wait), then
        re-raise the first worker error (unless ``raise_error=False``)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for st in self._stages.values():
            for t in st.threads:
                t.join(
                    timeout=None
                    if deadline is None
                    else max(0.0, deadline - time.monotonic())
                )
        # the graph stays in the (weak) crash-snapshot set until collected:
        # a fault just past join still shows the drained graph, which is
        # exactly what the flight recorder should say happened
        if raise_error and self._error is not None:
            raise RuntimeError(
                f"stage-graph '{self.name}' worker died"
            ) from self._error
        return self

    def running(self) -> bool:
        return any(t.is_alive() for st in self._stages.values() for t in st.threads)

    # -- the worker loop ---------------------------------------------------

    def _stopped(self) -> bool:
        return self._stop.is_set()

    def _run_worker(self, st: Stage, widx: int) -> None:
        from advanced_scrapper_tpu.obs import trace

        m_items, m_busy = self._stage_metrics(st.name)
        ctx = None
        slot = (st.name, widx)
        try:
            if st.worker_init is not None:
                ctx = st.worker_init()
            while not self._stop.is_set():
                if st.source is not None:
                    item = st.source()
                else:
                    item = st.in_edge.pop(timeout=0.5)
                if item is RETRY:
                    continue
                if item is DONE:
                    break
                if st.pausable and self.pause is not None:
                    self.pause.wait(should_stop=self._stopped)
                    if self._stop.is_set():
                        break
                self._in_flight[slot] = _describe(item)
                t0 = time.perf_counter()
                try:
                    if st.fn is None:
                        out = item
                    elif st.worker_init is not None:
                        out = st.fn(item, ctx)
                    else:
                        if trace.RECORDER.active and st.tag is not None:
                            with trace.span(
                                f"{self.name}.{st.name}", **(st.tag(item) or {})
                            ):
                                out = st.fn(item)
                        else:
                            out = st.fn(item)
                finally:
                    m_busy.inc(time.perf_counter() - t0)
                    self._in_flight.pop(slot, None)
                m_items.inc()
                if out is None or st.out_edge is None:
                    continue
                if st.fan_out:
                    for o in out:
                        if not st.out_edge.put(o):
                            break
                elif not st.out_edge.put(out):
                    # closed under us: the graph is stopping/failing
                    break
        except BaseException as e:
            self.fail(e)
        finally:
            if st.worker_close is not None and ctx is not None:
                try:
                    st.worker_close(ctx)
                except Exception:
                    pass
            last = False
            with self._lock:
                st.live -= 1
                last = st.live == 0
            if last and st.out_edge is not None:
                st.out_edge.producer_done()

    # -- observability -----------------------------------------------------

    def drain_snapshot(self) -> dict:
        """Whole-graph state for the flight recorder: per-stage live worker
        counts and in-flight item descriptions, per-edge depths."""
        stages = {}
        in_flight = dict(self._in_flight)
        for name, st in self._stages.items():
            stages[name] = {
                "workers": st.live,
                "in_flight": [
                    v for (s, _w), v in in_flight.items() if s == name
                ],
            }
        return {
            "graph": self.name,
            "instance": self._instance,
            "error": None if self._error is None else repr(self._error),
            "stages": stages,
            "edges": [e.snapshot() for e in self._edges.values()],
        }


def _locked_iter_source(items: Iterable) -> Callable:
    """Wrap an iterable as a thread-safe stage source: workers draw items
    under one lock (generators are not re-entrant), :data:`DONE` on
    exhaustion.  An exception raised by the iterator propagates out of
    the puller and fails the graph like any worker error."""
    it = iter(items)
    lock = threading.Lock()

    def _pull():
        with lock:
            return next(it, DONE)

    return _pull


def _describe(item) -> str:
    """A short, allocation-light description of an in-flight item for the
    crash snapshot (never the payload — a 100 kB article must not ride the
    ring buffer)."""
    try:
        if isinstance(item, (str, bytes)):
            return f"{type(item).__name__}[{len(item)}]"
        if isinstance(item, tuple):
            return f"tuple[{len(item)}]"
        return type(item).__name__
    except Exception:  # pragma: no cover - defensive
        return "?"


# -- crash-snapshot registry --------------------------------------------------

_live_lock = threading.Lock()
_LIVE: "weakref.WeakSet[StageGraph]" = weakref.WeakSet()
#: edges built OUTSIDE a StageGraph (lease queues, FanoutPool tasks) —
#: they have no graph to snapshot them, so the fault hook covers them
#: directly (touched ones only, capped, so the ring is never flooded)
_BARE_EDGES: "weakref.WeakSet[Edge]" = weakref.WeakSet()


def _register_graph(g: StageGraph) -> None:
    with _live_lock:
        _LIVE.add(g)


def live_graphs() -> list[StageGraph]:
    with _live_lock:
        return list(_LIVE)


def snapshot_all() -> list[dict]:
    """Drain snapshots of every live graph (newest-started last)."""
    return [g.drain_snapshot() for g in live_graphs()]


def _record_snapshots(recorder) -> None:
    """Fault hook: land every live graph's snapshot in the flight-recorder
    ring BEFORE the dump is written (so ``fsio._die`` deaths carry the
    whole-graph state).  Always records a ``graphs`` summary first — a
    fault that lands before any graph starts still proves the hook ran
    (``live=0``) — then one ``graph`` record per snapshot.  Must never
    raise — the crash owns control flow."""
    snaps = snapshot_all()
    recorder.record("snapshot", "graphs", live=len(snaps))
    for snap in snaps:
        recorder.record("snapshot", "graph", **snap)
    with _live_lock:
        bare = list(_BARE_EDGES)
    # only touched edges (something ever flowed or is buffered), capped:
    # a fault dump should show the lease backlog, not a wall of idle edges
    touched = [e.snapshot() for e in bare]
    touched = [s for s in touched if s["in"] or s["depth"]][:64]
    if touched:
        recorder.record("snapshot", "edges", edges=touched)


# registered at import time, not first-graph-start: a fault that lands
# before any graph exists still writes a (live=0) summary, so the sweep
# can tell "hook never ran" apart from "nothing was running"
def _install_fault_hook() -> None:
    from advanced_scrapper_tpu.obs import trace

    trace.add_fault_hook(_record_snapshots)


_install_fault_hook()


# -- bounded fan-out pool -----------------------------------------------------


class FanoutPool:
    """A tiny Edge-fed executor for bounded parallel fan-out.

    The index fleet's per-shard RPC fan-out (and any other remote hop)
    rides this instead of a bespoke ``ThreadPoolExecutor``: the task queue
    is a runtime :class:`Edge`, so depth/stall telemetry and the crash
    snapshot see remote work exactly like local stage work.
    """

    def __init__(self, workers: int, *, name: str = "fanout"):
        from concurrent.futures import Future

        self._Future = Future
        self.name = name
        self._tasks = Edge(f"{name}.tasks", None, graph=name)
        self._tasks.register_producer()
        self._threads = [
            threading.Thread(
                target=self._run, name=f"astpu-{name}-{i}", daemon=True
            )
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            item = self._tasks.pop()
            if item is DONE:
                return
            fut, fn, args, kw = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args, **kw))
            except BaseException as e:
                fut.set_exception(e)

    def submit(self, fn, *args, **kw):
        fut = self._Future()
        if not self._tasks.put((fut, fn, args, kw)):
            raise RuntimeError(f"FanoutPool '{self.name}' is shut down")
        return fut

    def map(self, fn, items: Iterable):
        return [self.submit(fn, it) for it in items]

    def shutdown(self, wait: bool = True) -> None:
        self._tasks.producer_done()
        if wait:
            for t in self._threads:
                t.join(timeout=30)
