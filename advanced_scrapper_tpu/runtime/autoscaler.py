"""Pressure-driven fleet autoscaler — the policy half of elastic resharding.

:class:`Autoscaler` watches the admission plane's pressure signal (the
same 0..1 number the :class:`~.admission.DegradationLadder` brownouts
on, readable fleet-wide via :func:`admission_pressure` over the SLO
engine's registry view) and decides WHEN the fleet should change shard
count; the mechanism — the live N→M cutover — belongs to
``index/fleet.py``'s ``reshard_to`` and is injected as callbacks, so
this module stays a pure, clock-driven state machine (trivially testable
with a fake clock, usable against any resharder).

Flap resistance is the whole design, borrowed step-for-step from the
``DegradationLadder``:

- **enter/exit hysteresis** — scale-out arms at ``out_at`` and the armed
  timer survives dips down to ``out_exit``; scale-in arms at ``in_at``
  and survives rises up to ``in_exit``.  Between the two hold bands sits
  the middle band, which resets BOTH timers — an oscillating load that
  keeps re-crossing a threshold never accumulates dwell.
- **dwell** — a threshold must hold (within its band) for ``dwell_s``
  continuous seconds before a transition fires; at most one transition
  per observation.
- **cooldown** — after ANY transition, ``cooldown_s`` must elapse before
  the next one; a reshard is minutes of background streaming and the
  signal it changes lags, so back-to-back topology changes are noise.
- **SLO gate** — scale-in (capacity REMOVAL) additionally requires the
  SLO engine (when wired) to report healthy; violating SLOs while under
  the low-pressure threshold means something else is wrong, and taking
  capacity away is the one move guaranteed to make it worse.

Layering: runtime sits above ``obs`` only — no index/, net/, tools/
imports (enforced by ``tools/lint_imports.py``).  The fleet hands its
``reshard_to`` in as a closure; this module never sees a socket.
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "Autoscaler",
    "admission_pressure",
]


def admission_pressure(samples=None) -> float:
    """Fleet-wide pressure signal: the max ``astpu_admission_pressure``
    gauge across every admission gate currently exporting (the fullest
    gate is the one a scale-out must relieve).  ``samples`` is an
    iterable of ``(name, labels, value)`` — pass
    ``SloEngine.registry_samples()`` output, or None to read the live
    registry directly.  0.0 when no gate exports (nothing to react to).
    """
    if samples is None:
        from advanced_scrapper_tpu.obs.slo import SloEngine

        samples = SloEngine.registry_samples()
    best = 0.0
    for name, _labels, value in samples:
        if name == "astpu_admission_pressure":
            best = max(best, float(value))
    return best


def _fresh_handles(obj) -> None:
    from advanced_scrapper_tpu.obs import telemetry

    if obj._gen != telemetry.REGISTRY.generation:
        obj._instrument()


class Autoscaler:
    """Hysteretic scale-out/scale-in decider over a pressure signal.

    ``scale_out(target)`` / ``scale_in(target)`` perform the topology
    change to ``target`` shards (for the fleet: build the new spec and
    call ``reshard_to``); a callback raising propagates to the
    ``observe`` caller and the transition is NOT recorded — the next
    dwell re-attempts.  Targets double going out and halve coming in
    (clamped to ``[min_shards, max_shards]``): ring math makes any N→M
    legal, but power-of-two steps keep successive reshards moving
    disjoint arc sets.

    Thresholds must satisfy
    ``in_at ≤ in_exit < out_exit ≤ out_at`` — two hold bands separated
    by a dead middle band.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        shards: int,
        *,
        scale_out,
        scale_in,
        out_at: float = 0.7,
        out_exit: float = 0.4,
        in_at: float = 0.15,
        in_exit: float = 0.3,
        dwell_s: float = 30.0,
        cooldown_s: float = 300.0,
        min_shards: int = 1,
        max_shards: int = 64,
        slo_engine=None,
        clock=time.monotonic,
        name: str | None = None,
    ):
        if not (in_at <= in_exit < out_exit <= out_at):
            raise ValueError(
                f"autoscaler thresholds must order in_at ≤ in_exit < "
                f"out_exit ≤ out_at, got {in_at}/{in_exit}/{out_exit}/{out_at}"
            )
        if not (1 <= min_shards <= shards <= max_shards):
            raise ValueError(
                f"need 1 ≤ min_shards ≤ shards ≤ max_shards, got "
                f"{min_shards}/{shards}/{max_shards}"
            )
        self.shards = int(shards)
        self._scale_out = scale_out
        self._scale_in = scale_in
        self.out_at = float(out_at)
        self.out_exit = float(out_exit)
        self.in_at = float(in_at)
        self.in_exit = float(in_exit)
        self.dwell_s = float(dwell_s)
        self.cooldown_s = float(cooldown_s)
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        self.slo_engine = slo_engine
        self._clock = clock
        self._lock = threading.Lock()
        self._out_since: float | None = None  # pressure in the out band since
        self._in_since: float | None = None   # pressure in the in band since
        self._cooldown_until: float | None = None
        self._last_pressure = 0.0
        with Autoscaler._seq_lock:
            if not name:
                name = f"autoscaler{Autoscaler._seq}"
            Autoscaler._seq += 1
        self.name = name
        self._instrument()

    # -- telemetry ---------------------------------------------------------

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._gen = telemetry.REGISTRY.generation
        # always-on: topology changes are exactly what an operator audits
        self._m_trans = {
            d: telemetry.REGISTRY.counter(
                "astpu_autoscale_transitions_total",
                "fleet topology changes the autoscaler committed",
                always=True, scaler=self.name, dir=d,
            )
            for d in ("out", "in")
        }
        self._m_blocked = {}
        telemetry.REGISTRY.gauge_fn(
            "astpu_autoscale_pressure",
            lambda s: s._last_pressure,
            owner=self, scaler=self.name,
            help="last pressure sample the autoscaler observed",
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_autoscale_target_shards",
            lambda s: s.shards,
            owner=self, always=True, scaler=self.name,
            help="shard count the autoscaler currently stands behind",
        )

    def _count_blocked(self, reason: str) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        c = self._m_blocked.get(reason)
        if c is None:
            c = telemetry.REGISTRY.counter(
                "astpu_autoscale_blocked_total",
                "dwell-complete transitions vetoed (cooldown active, SLO "
                "unhealthy, or shard bounds reached)",
                always=True, scaler=self.name, reason=reason,
            )
            self._m_blocked[reason] = c
        c.inc()

    # -- state machine -----------------------------------------------------

    def _slo_healthy(self, slo_ok) -> bool:
        if slo_ok is not None:
            return bool(slo_ok)
        if self.slo_engine is None:
            return True
        try:
            return bool(self.slo_engine.evaluate().get("ok", True))
        except Exception:
            return False  # an unreadable SLO plane never green-lights removal

    def observe(
        self, pressure: float, *, now: float | None = None, slo_ok=None
    ) -> str:
        """Feed one pressure sample; returns ``"out"``, ``"in"``, or
        ``"none"``.  At most one transition per call; a transition's
        callback runs synchronously under the decision (the reshard it
        triggers IS the slow part — callers wanting it off-thread wrap
        the callback)."""
        _fresh_handles(self)
        if now is None:
            now = self._clock()
        pressure = float(pressure)
        fire = None
        blocked = None
        target = self.shards
        with self._lock:
            self._last_pressure = pressure
            if pressure >= self.out_at:
                # the out band: arm (the timer survives dips to out_exit)
                self._in_since = None
                if self._out_since is None:
                    self._out_since = now
            elif pressure <= self.in_at:
                self._out_since = None
                if self._in_since is None:
                    self._in_since = now
            else:
                # hold bands keep an armed timer alive; the middle band
                # resets both — oscillation never accumulates dwell
                if pressure <= self.out_exit:
                    self._out_since = None
                if pressure >= self.in_exit:
                    self._in_since = None
            cooling = (
                self._cooldown_until is not None
                and now < self._cooldown_until
            )
            if (
                self._out_since is not None
                and now - self._out_since >= self.dwell_s
            ):
                if self.shards >= self.max_shards:
                    blocked = "bounds"
                    self._out_since = None
                elif cooling:
                    blocked = "cooldown"
                else:
                    fire = "out"
                    target = min(self.max_shards, self.shards * 2)
            elif (
                self._in_since is not None
                and now - self._in_since >= self.dwell_s
            ):
                if self.shards <= self.min_shards:
                    blocked = "bounds"
                    self._in_since = None
                elif cooling:
                    blocked = "cooldown"
                elif not self._slo_healthy(slo_ok):
                    # capacity removal under an unhealthy SLO is the one
                    # move guaranteed to make the violation worse
                    blocked = "slo"
                else:
                    fire = "in"
                    target = max(self.min_shards, self.shards // 2)
        if blocked is not None:
            self._count_blocked(blocked)
            return "none"
        if fire is None:
            return "none"
        # the callback runs OUTSIDE the lock (it may be minutes of
        # migration); a raise propagates with the timers still armed, so
        # the next dwell re-attempts
        if fire == "out":
            self._scale_out(target)
        else:
            self._scale_in(target)
        with self._lock:
            self.shards = target
            self._out_since = None
            self._in_since = None
            self._cooldown_until = now + self.cooldown_s
        self._m_trans[fire].inc()
        from advanced_scrapper_tpu.obs import trace

        trace.record(
            "event", "autoscale.transition", scaler=self.name,
            dir=fire, shards=target,
        )
        return fire

    def status(self) -> dict:
        """JSON-able view for ``/status`` dashboards."""
        with self._lock:
            now = self._clock()
            return {
                "scaler": self.name,
                "shards": self.shards,
                "pressure": self._last_pressure,
                "out_armed_s": (
                    now - self._out_since
                    if self._out_since is not None else None
                ),
                "in_armed_s": (
                    now - self._in_since
                    if self._in_since is not None else None
                ),
                "cooldown_s": (
                    max(0.0, self._cooldown_until - now)
                    if self._cooldown_until is not None else 0.0
                ),
            }
