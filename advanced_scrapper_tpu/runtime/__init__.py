"""Backpressured stage-graph runtime — ONE scheduler owning every queue.

Before this package, the five async layers (feed, dedup, matcher, scraper,
encode) each hand-rolled their own queues, worker threads, pause logic and
shutdown ordering — five slightly different answers to the same four
questions (how do items move, what bounds them, who wakes whom, and in what
order does it all stop).  The runtime answers them once:

- :class:`Edge` — a named bounded FIFO.  Puts block when full
  (backpressure), batch pops support the full-tile ``min_fill`` discipline
  the device feed needs, closes are one-way and wake everybody, and every
  edge exports depth/stall telemetry for free.
- :class:`StageGraph` — stages declare inputs/outputs/parallelism; the
  graph owns the worker threads, the error fan-out (first failure stops
  the whole graph, no stranded consumers), the pause gate, ordered
  drain-at-close (an edge auto-closes when its last producer exits), and
  a whole-graph :meth:`StageGraph.drain_snapshot` that lands in the
  ``obs/trace`` flight recorder before a chaos death (``fsio._die``).
- :class:`PauseGate` — the deadline-based global pause (the scraper's
  rate-limit circuit breaker), now a runtime primitive any stage can honour.
- :class:`AdmissionController` / :class:`DegradationLadder`
  (``runtime/admission.py``) — the overload plane: token-bucket +
  concurrency + queue-depth admission with priority classes and counted
  retry-after rejects (PauseGate generalized; its surface and telemetry
  names flow through), plus declared brownout steps with enter/exit
  hysteresis that consumers (RPC server, shard server, lease server,
  dedup engine) honour at their decision points.
- :class:`Autoscaler` (``runtime/autoscaler.py``) — the elastic-fleet
  policy head: watches admission pressure (and the SLO engine) and
  decides WHEN shard counts change, with ladder-style enter/exit
  hysteresis + dwell + cooldown so oscillating load never flaps
  topology; the HOW (live resharding) is injected as callbacks.
- :class:`FanoutPool` — a tiny Edge-fed executor for bounded parallel
  fan-out (the index fleet's per-shard RPCs ride it), so remote hops use
  the same queue abstraction as local stages.

Graphs are cheap enough to be EPHEMERAL: the pipelined dispatch executor
(``pipeline/dispatch.py``) builds one per dedup corpus ("dedup.h2d") and
one per matcher chunk ("matcher.h2d") — threads spawn at
:meth:`StageGraph.start`, die at join, and the flight-recorder registry
holds graphs weakly, so a firehose of short-lived graphs neither leaks
nor hides (``obs_top --graph`` shows whichever are live; same-named
successors simply take over the telemetry series, latest-wins).

Layering: the runtime sits above ``obs`` only — it must never import
``pipeline``/``extractors``/``net``/``index`` (enforced by
``tools/lint_imports.py``); those layers import *it*.
"""

from advanced_scrapper_tpu.runtime.admission import (
    PRIORITY_CRITICAL,
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    AdmissionController,
    AdmissionDecision,
    DegradationLadder,
    LadderStep,
)
from advanced_scrapper_tpu.runtime.autoscaler import (
    Autoscaler,
    admission_pressure,
)
from advanced_scrapper_tpu.runtime.graph import (
    DONE,
    RETRY,
    Edge,
    EdgeClosed,
    FanoutPool,
    StageGraph,
    live_graphs,
    snapshot_all,
)
from advanced_scrapper_tpu.runtime.pause import PauseGate

__all__ = [
    "DONE",
    "PRIORITY_CRITICAL",
    "PRIORITY_HIGH",
    "PRIORITY_LOW",
    "PRIORITY_NORMAL",
    "RETRY",
    "AdmissionController",
    "AdmissionDecision",
    "Autoscaler",
    "DegradationLadder",
    "Edge",
    "EdgeClosed",
    "FanoutPool",
    "LadderStep",
    "PauseGate",
    "StageGraph",
    "admission_pressure",
    "live_graphs",
    "snapshot_all",
]
