"""Deadline-based global pause — the runtime's pause/resume primitive.

Absorbed from ``pipeline/scraper.py`` (its rate-limit circuit breaker,
itself the race-free successor of the reference's unlocked global
``pause`` flag read by three threads): a :class:`PauseGate` owns a
monotonic deadline behind a lock; any trigger extends it, never shortens
it, and every stage that declared itself ``pausable`` honours it between
popping an item and working on it.  The scraper keeps its historical
telemetry names by default; other graphs can rename the event/counter at
construction.
"""

from __future__ import annotations

import threading
import time

__all__ = ["PauseGate"]


class PauseGate:
    """Deadline-based global pause (race-free successor of ref :30)."""

    def __init__(
        self,
        clock=time.monotonic,
        *,
        counter: str = "astpu_rate_limit_trips_total",
        counter_help: str = "rate-limit circuit-breaker trips",
        event: str = "scraper.rate_limit_trip",
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._until = 0.0
        self.trips = 0
        self._counter_name = counter
        self._counter_help = counter_help
        self._event_name = event

    def trigger(self, duration: float) -> None:
        from advanced_scrapper_tpu.obs import telemetry, trace

        with self._lock:
            self._until = max(self._until, self._clock() + duration)
            self.trips += 1
        # a circuit-breaker trip is exactly the rare event the telemetry
        # plane exists for: always counted, and on the flight recorder so
        # a crash dump shows whether the fleet died paused
        telemetry.event_counter(self._counter_name, self._counter_help).inc()
        trace.record("event", self._event_name, wait_s=duration)

    def remaining(self) -> float:
        with self._lock:
            return max(0.0, self._until - self._clock())

    def wait(
        self, sleep=time.sleep, tick: float = 1.0, should_stop=lambda: False
    ) -> None:
        while not should_stop():
            r = self.remaining()
            if r <= 0:
                return
            sleep(min(tick, r))
