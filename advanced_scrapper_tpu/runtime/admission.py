"""Admission control + declared graceful degradation — the overload plane.

Two primitives, both runtime-level (no pipeline/net/index imports, same
layering as the rest of ``runtime/``):

:class:`AdmissionController` is the reference's 200 s pause circuit
(``PauseGate``, itself the scraper's rate-limit breaker) industrialized
into a real admission decision: a token-bucket **rate** limit, a
**concurrency** (in-flight) limit and a caller-reported **queue-depth**
limit, evaluated per request under a **priority class**
(:data:`PRIORITY_CRITICAL` health probes are never refused; the lowest
class is shed first).  Every refusal is a *counted reject carrying a
retry-after hint* — the difference between overload and death: an
overloaded server says "no, come back in 80 ms" and stays provably
alive, instead of timing out and getting failed over (which amplifies
the storm onto the survivors).  The PauseGate surface
(``trigger``/``remaining``/``wait``, and its telemetry names) is kept
byte-stable: a triggered pause is just one more reason to reject, so
the scraper's circuit breaker is one *configuration* of this class.

:class:`DegradationLadder` maps **sustained** pressure to declared
brownout steps — shrink the dispatch window, skip the rerank hook,
probe fewer LSH bands, shed lowest-priority work — each a counted,
reversible transition.  Steps arm in order with enter/exit hysteresis
(distinct thresholds plus a dwell time), so oscillating load cannot
flap a step on and off; consumers ask ``ladder.active("skip_rerank")``
at their decision point and count the shed work via
:meth:`DegradationLadder.count_effect`.

Telemetry (all always-on — a reject during an incident must be visible
even with ``ASTPU_TELEMETRY`` off, exactly like the device counters):
``astpu_admission_requests_total{gate,outcome,class}``,
``astpu_admission_rejected_total{gate,reason}``,
``astpu_admission_retry_after_seconds{gate}``,
``astpu_admission_inflight{gate}``, ``astpu_admission_pressure{gate}``,
``astpu_degraded_step{ladder}``,
``astpu_degraded_transitions_total{ladder,step,dir}``,
``astpu_degraded_effects_total{ladder,step}``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from advanced_scrapper_tpu.runtime.pause import PauseGate

__all__ = [
    "PRIORITY_CRITICAL",
    "PRIORITY_HIGH",
    "PRIORITY_LOW",
    "PRIORITY_NORMAL",
    "AdmissionController",
    "AdmissionDecision",
    "DEFAULT_LADDER_STEPS",
    "DegradationLadder",
    "LadderStep",
]

#: priority classes: smaller = more important.  CRITICAL (health pings,
#: promotion probes) is never refused — the one class overload must keep
#: answering, or overload becomes indistinguishable from death.
PRIORITY_CRITICAL = 0
PRIORITY_HIGH = 1
PRIORITY_NORMAL = 2
PRIORITY_LOW = 3

_CLASS_NAMES = {0: "critical", 1: "high", 2: "normal", 3: "low"}


def _class_name(priority: int) -> str:
    return _CLASS_NAMES.get(int(priority), str(int(priority)))


def _fresh_handles(obj) -> None:
    """Lazily re-instrument after a ``Registry.reset()`` (tests) —
    controllers and ladders cache metric HANDLES at construction, and a
    reset would otherwise orphan them: later rejects/transitions would
    increment counters the registry no longer exports (the same trap
    ``obs/stages.py`` retired with its reset hook).  Lazy — checked at
    each use site — so a dormant object never re-pollutes a freshly
    reset registry; only ones still actively deciding re-register."""
    from advanced_scrapper_tpu.obs import telemetry

    if obj._gen != telemetry.REGISTRY.generation:
        obj._instrument()


@dataclass(frozen=True)
class AdmissionDecision:
    """One admission verdict.  Truthy iff admitted; a reject carries the
    machine-readable ``reason`` and a ``retry_after`` hint (seconds) the
    caller is expected to honor before retrying."""

    admitted: bool
    reason: str = ""
    retry_after: float = 0.0
    priority: int = PRIORITY_NORMAL
    #: True when this decision consumed an in-flight slot — the caller
    #: must hand the decision back via :meth:`AdmissionController.release`
    slot: bool = False

    def __bool__(self) -> bool:
        return self.admitted


class AdmissionController:
    """Token-bucket + concurrency + queue-depth admission with priority
    classes, counted rejects and retry-after hints.

    ``rate``/``burst`` bound sustained request throughput (0 = no rate
    limit); ``max_inflight`` bounds concurrently admitted work (0 = no
    limit; callers MUST :meth:`release` every admitted decision);
    ``max_queue`` rejects when the caller-reported queue depth reaches it
    (0 = no limit).  ``ladder`` (optional) receives a pressure
    observation per decision and, once its ``shed_step`` is active,
    requests with ``priority >= shed_at`` are refused outright.

    The PauseGate compatibility surface: :meth:`trigger`,
    :meth:`remaining` and :meth:`wait` delegate to an embedded
    :class:`PauseGate` constructed with the SAME default telemetry names
    (``astpu_rate_limit_trips_total`` / ``scraper.rate_limit_trip``), and
    an active pause rejects every non-critical request with the pause's
    remaining time as the retry-after hint.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        *,
        rate: float = 0.0,
        burst: float | None = None,
        max_inflight: int = 0,
        max_queue: int = 0,
        base_retry_after: float = 0.05,
        ladder: "DegradationLadder | None" = None,
        shed_at: int = PRIORITY_LOW,
        shed_step: str = "shed_low",
        name: str = "",
        clock=time.monotonic,
        pause_counter: str = "astpu_rate_limit_trips_total",
        pause_counter_help: str = "rate-limit circuit-breaker trips",
        pause_event: str = "scraper.rate_limit_trip",
    ):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, self.rate))
        self.max_inflight = int(max_inflight)
        self.max_queue = int(max_queue)
        self.base_retry_after = float(base_retry_after)
        self.ladder = ladder
        self.shed_at = int(shed_at)
        self.shed_step = shed_step
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._refill_at = clock()
        self._inflight = 0
        self._pressure = 0.0
        self.admitted = 0
        self.rejected = 0
        # the embedded circuit breaker — PauseGate semantics byte-stable
        # (trigger extends, never shortens; telemetry names preserved)
        self.gate = PauseGate(
            clock=clock,
            counter=pause_counter,
            counter_help=pause_counter_help,
            event=pause_event,
        )
        with AdmissionController._seq_lock:
            if not name:
                name = f"adm{AdmissionController._seq}"
            AdmissionController._seq += 1
        self.name = name
        self._instrument()

    # -- telemetry ---------------------------------------------------------

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._gen = telemetry.REGISTRY.generation
        g = self.name
        self._m_req = {}  # (outcome, class) → always-on counter
        for outcome in ("admitted", "rejected"):
            for cls in _CLASS_NAMES.values():
                self._m_req[(outcome, cls)] = telemetry.REGISTRY.counter(
                    "astpu_admission_requests_total",
                    "admission decisions, by outcome and priority class",
                    always=True, gate=g, outcome=outcome, **{"class": cls},
                )
        self._m_rej = {}  # reason → always-on counter (lazy: 4 reasons max)
        # every admission series is always-on (the module contract): an
        # incident with ASTPU_TELEMETRY off must still show the hint
        # distribution and the live pressure, not just the reject counts
        self._m_retry_after = telemetry.REGISTRY.histogram(
            "astpu_admission_retry_after_seconds",
            "retry-after hints handed to rejected requests",
            always=True, gate=g,
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_admission_inflight",
            lambda s: s._inflight,
            owner=self, always=True, gate=g,
            help="admitted requests currently in flight",
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_admission_pressure",
            lambda s: round(s._pressure, 4),
            owner=self, always=True, gate=g,
            help="most recent pressure observation (0..1+)",
        )

    def _count_reject(self, reason: str) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        c = self._m_rej.get(reason)
        if c is None:
            c = telemetry.REGISTRY.counter(
                "astpu_admission_rejected_total",
                "admission rejects, by reason",
                always=True, gate=self.name, reason=reason,
            )
            self._m_rej[reason] = c
        c.inc()

    # -- PauseGate compatibility surface -----------------------------------

    def trigger(self, duration: float) -> None:
        """Trip the circuit breaker (PauseGate semantics: the deadline
        only ever extends)."""
        self.gate.trigger(duration)

    def remaining(self) -> float:
        return self.gate.remaining()

    def wait(self, sleep=time.sleep, tick: float = 1.0, should_stop=lambda: False) -> None:
        self.gate.wait(sleep=sleep, tick=tick, should_stop=should_stop)

    @property
    def trips(self) -> int:
        return self.gate.trips

    # -- the decision ------------------------------------------------------

    def admit(
        self,
        priority: int = PRIORITY_NORMAL,
        *,
        queue_depth: int | None = None,
    ) -> AdmissionDecision:
        """One admission decision.  Critical requests are always admitted
        (and never consume a token or an in-flight slot — a health probe
        must stay answerable at any depth of overload).  Admitted
        decisions with ``slot=True`` MUST be handed back via
        :meth:`release` when the work completes."""
        _fresh_handles(self)
        now = self._clock()
        priority = int(priority)
        if priority <= PRIORITY_CRITICAL:
            d = AdmissionDecision(True, priority=priority)
            # pressure=None: a critical bypass carries NO load signal —
            # feeding the ladder a synthetic 0.0 here would read as
            # "calm" and reset the dwell timers mid-storm (health pings
            # arrive faster than the dwell, so brownout steps could
            # never arm while the system saturates)
            self._account(d, None)
            return d
        reason = ""
        retry_after = 0.0
        with self._lock:
            # token refill first: pressure reads below see current tokens
            if self.rate > 0:
                self._tokens = min(
                    self.burst,
                    self._tokens + (now - self._refill_at) * self.rate,
                )
                self._refill_at = now
            paused = self.gate.remaining()
            if paused > 0:
                reason, retry_after = "paused", paused
            elif (
                self.ladder is not None
                and priority >= self.shed_at
                and self.ladder.active(self.shed_step)
            ):
                reason, retry_after = "shed", 4 * self.base_retry_after
            elif self.max_inflight > 0 and self._inflight >= self.max_inflight:
                reason = "concurrency"
                retry_after = self.base_retry_after * (
                    1 + self._inflight - self.max_inflight
                )
            elif (
                self.max_queue > 0
                and queue_depth is not None
                and queue_depth >= self.max_queue
            ):
                reason, retry_after = "queue", 2 * self.base_retry_after
            elif self.rate > 0 and self._tokens < 1.0:
                reason = "rate"
                retry_after = (1.0 - self._tokens) / self.rate
            admitted = not reason
            if admitted:
                if self.rate > 0:
                    self._tokens -= 1.0
                self._inflight += 1
            # a SHED reject is the ladder's own output — feeding it back
            # as pressure 1.0 would hold the shed step armed for as long
            # as refused clients keep retrying (a livelock: the step
            # could never exit).  Capacity rejects DO read as full
            # pressure; shed rejects read the raw utilization, which
            # falls as the bucket refills and lets the step disarm.
            pressure = self._pressure_locked(
                queue_depth, rejected=bool(reason) and reason != "shed"
            )
            self._pressure = pressure
        d = AdmissionDecision(
            admitted,
            reason=reason,
            retry_after=round(retry_after, 6),
            priority=priority,
            slot=admitted,
        )
        self._account(d, pressure)
        return d

    def _pressure_locked(self, queue_depth, *, rejected: bool) -> float:
        """Scalar load signal in [0, 1+]: the max utilization across the
        declared limits; a reject reads as full pressure (1.0) so the
        ladder sees sustained refusal even when no single limit exposes
        a smooth utilization."""
        parts = [0.0]
        if self.max_inflight > 0:
            parts.append(self._inflight / self.max_inflight)
        if self.max_queue > 0 and queue_depth is not None:
            parts.append(queue_depth / self.max_queue)
        if self.rate > 0 and self.burst > 0:
            parts.append(1.0 - self._tokens / self.burst)
        if rejected:
            parts.append(1.0)
        return max(parts)

    def _account(self, d: AdmissionDecision, pressure: float) -> None:
        outcome = "admitted" if d.admitted else "rejected"
        cls = _class_name(d.priority)
        c = self._m_req.get((outcome, cls))
        if c is None:  # numeric class outside the named four
            from advanced_scrapper_tpu.obs import telemetry

            c = telemetry.REGISTRY.counter(
                "astpu_admission_requests_total",
                "admission decisions, by outcome and priority class",
                always=True, gate=self.name, outcome=outcome,
                **{"class": cls},
            )
            self._m_req[(outcome, cls)] = c
        c.inc()
        if d.admitted:
            self.admitted += 1
        else:
            self.rejected += 1
            self._count_reject(d.reason)
            self._m_retry_after.observe(d.retry_after)
        if self.ladder is not None and pressure is not None:
            self.ladder.observe(pressure, now=self._clock())

    def release(self, decision: AdmissionDecision | None = None) -> None:
        """Hand back an admitted in-flight slot.  Accepts the decision
        (preferred: critical admissions hold no slot) or nothing (legacy
        call sites that know they were admitted non-critically)."""
        if decision is not None and not decision.slot:
            return
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def pressure(self) -> float:
        with self._lock:
            return self._pressure


# -- the brownout ladder ------------------------------------------------------


@dataclass(frozen=True)
class LadderStep:
    """One declared brownout step: arms when pressure holds at or above
    ``enter_at``, disarms when it holds at or below ``exit_at`` (the gap
    is the hysteresis band; the ladder's dwell time is the other half)."""

    name: str
    enter_at: float
    exit_at: float


#: the default brownout sequence, cheapest reversible degradation first:
#: shrink the dispatch window (less in-flight device memory), skip the
#: rerank tier (precision brownout), probe fewer LSH bands (recall
#: brownout), shed lowest-priority work outright.
DEFAULT_LADDER_STEPS = (
    LadderStep("shrink_window", 0.70, 0.45),
    LadderStep("skip_rerank", 0.85, 0.55),
    LadderStep("fewer_bands", 0.93, 0.65),
    LadderStep("shed_low", 0.98, 0.75),
)


class DegradationLadder:
    """Sustained pressure → ordered, counted, reversible brownout steps.

    ``observe(pressure)`` drives a small state machine: the NEXT step arms
    only after pressure has held at/above its ``enter_at`` for ``dwell_s``
    continuous seconds, and the CURRENT step disarms only after pressure
    has held at/below its ``exit_at`` for ``dwell_s`` — so a load signal
    oscillating faster than the dwell can never flap a step (each
    crossing into the middle band resets both timers).  Steps arm and
    disarm strictly in declaration order: ``level() == k`` means exactly
    ``steps[:k]`` are active.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        steps=DEFAULT_LADDER_STEPS,
        *,
        dwell_s: float = 1.0,
        clock=time.monotonic,
        name: str = "",
    ):
        steps = tuple(steps)
        if not steps:
            raise ValueError("a ladder needs at least one step")
        for st in steps:
            if st.exit_at >= st.enter_at:
                raise ValueError(
                    f"step {st.name!r}: exit_at {st.exit_at} must sit BELOW "
                    f"enter_at {st.enter_at} (the hysteresis band)"
                )
        for a, b in zip(steps, steps[1:]):
            if b.enter_at < a.enter_at:
                raise ValueError(
                    f"steps must escalate: {b.name!r} enters at {b.enter_at} "
                    f"below {a.name!r}'s {a.enter_at}"
                )
        self.steps = steps
        self.dwell_s = float(dwell_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._arm_since: float | None = None   # pressure ≥ next enter_at since
        self._calm_since: float | None = None  # pressure ≤ current exit_at since
        with DegradationLadder._seq_lock:
            if not name:
                name = f"ladder{DegradationLadder._seq}"
            DegradationLadder._seq += 1
        self.name = name
        self._instrument()

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        self._gen = telemetry.REGISTRY.generation
        telemetry.REGISTRY.gauge_fn(
            "astpu_degraded_step",
            lambda s: s._level,
            owner=self, always=True, ladder=self.name,
            help="active brownout steps (0 = full service)",
        )
        self._m_trans: dict[tuple[str, str], object] = {}
        self._m_effects: dict[str, object] = {}

    def _count_transition(self, step: str, direction: str) -> None:
        from advanced_scrapper_tpu.obs import telemetry, trace

        key = (step, direction)
        c = self._m_trans.get(key)
        if c is None:
            c = telemetry.REGISTRY.counter(
                "astpu_degraded_transitions_total",
                "brownout step transitions, by step and direction",
                always=True, ladder=self.name, step=step, dir=direction,
            )
            self._m_trans[key] = c
        c.inc()
        trace.record(
            "event", f"degrade.{direction}", ladder=self.name, step=step,
            level=self._level,
        )

    def count_effect(self, step: str, n: int = 1) -> None:
        """Count work actually degraded under an active step — the
        consumer-side half of the ledger (transitions say the step armed;
        effects say it changed real work)."""
        _fresh_handles(self)
        from advanced_scrapper_tpu.obs import telemetry

        c = self._m_effects.get(step)
        if c is None:
            c = telemetry.REGISTRY.counter(
                "astpu_degraded_effects_total",
                "work items degraded under an active brownout step",
                always=True, ladder=self.name, step=step,
            )
            self._m_effects[step] = c
        c.inc(n)

    # -- state machine -----------------------------------------------------

    def observe(self, pressure: float, now: float | None = None) -> int:
        """Feed one pressure sample; returns the (possibly new) level.
        At most one transition per call — a pressure spike cannot slam
        the ladder to the top in one observation."""
        _fresh_handles(self)
        if now is None:
            now = self._clock()
        entered = exited = None
        with self._lock:
            lvl = self._level
            climbing = (
                lvl < len(self.steps)
                and pressure >= self.steps[lvl].enter_at
            )
            calming = lvl > 0 and pressure <= self.steps[lvl - 1].exit_at
            if climbing:
                self._calm_since = None
                if self._arm_since is None:
                    self._arm_since = now
                elif now - self._arm_since >= self.dwell_s:
                    self._level += 1
                    entered = self.steps[lvl].name
                    self._arm_since = None
            elif calming:
                self._arm_since = None
                if self._calm_since is None:
                    self._calm_since = now
                elif now - self._calm_since >= self.dwell_s:
                    self._level -= 1
                    exited = self.steps[lvl - 1].name
                    self._calm_since = None
            else:
                # the middle band: neither threshold holds — reset both
                # dwell timers (this is what makes oscillation flap-free)
                self._arm_since = None
                self._calm_since = None
            out = self._level
        if entered is not None:
            self._count_transition(entered, "enter")
        if exited is not None:
            self._count_transition(exited, "exit")
        return out

    def level(self) -> int:
        with self._lock:
            return self._level

    def active(self, step_name: str) -> bool:
        """Is the named step currently armed?"""
        with self._lock:
            for i, st in enumerate(self.steps):
                if st.name == step_name:
                    return i < self._level
        return False

    def active_steps(self) -> list[str]:
        with self._lock:
            return [st.name for st in self.steps[: self._level]]

    def status(self) -> dict:
        with self._lock:
            return {
                "ladder": self.name,
                "level": self._level,
                "active": [st.name for st in self.steps[: self._level]],
                "steps": [
                    {"name": st.name, "enter_at": st.enter_at, "exit_at": st.exit_at}
                    for st in self.steps
                ],
            }
