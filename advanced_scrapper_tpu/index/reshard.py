"""Live N→M resharding: the plan math and the crash-safe cutover ledger.

The consistent-hash ring (``fleet.ring_assign``) froze the shard count at
:class:`~advanced_scrapper_tpu.index.fleet.FleetSpec` construction; this
module is the pure half of lifting that — everything a topology change
needs that is NOT a remote call:

- :func:`plan_reshard` — diff the old ring against the new one into a
  minimal set of :class:`MigrationRange` arcs (mixed/ring space, disjoint,
  sorted).  Ring points depend only on ``(shard, vnode)``, so a split's
  new points interleave with the old ones and only the arcs whose owner
  actually changes ever move — the consistent-hash promise, made explicit.
- :class:`RangeTable` — the vectorized per-key router the fleet consults
  on every probe/insert while a reshard is live: a key's ring position
  falls in a migrating arc ⇒ route by that arc's cutover state (reads
  from the OLD owner until the flip, writes dual-applied during the
  dual-write window), else the old ring answers unchanged.
- :class:`ReshardLedger` — the migration WAL.  One atomically-replaced
  JSON document holding every range's cutover state
  (``pending → dual_write → flipped → retired``); a crash at ANY instant
  leaves either the previous whole document or the next one, so a
  half-flipped range is unrepresentable on disk.  Resume voids every
  non-flipped range back to ``pending`` (the armed-ledger discipline the
  fleet's resync uses: progress that was not sealed never counts) and
  keeps every flipped one — the flip write IS the commit point.

Who owns a range when (the cutover lifecycle the fleet drives):

====================  ===========  ======================  =============
state                 reads        writes                  on crash
====================  ===========  ======================  =============
``pending``           src          src                     nothing moved
``dual_write``        src          src (acked) + dst       void → pending
``flipped``           dst          dst                     keep; re-retire
``retired``           dst          dst (src drops range)   keep
====================  ===========  ======================  =============

Layering: plan/ledger math only — numpy + storage + obs.  The RPCs that
act on a plan (mixed digests, paged range fetches, retire marks) live in
``fleet.py``/``remote.py``; this module must not touch the transport
(enforced by a per-module ``tools/lint_imports.py`` rule: not even the
``net.rpc`` exemption the rest of ``index/`` enjoys).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

import numpy as np

from advanced_scrapper_tpu.index.repair import KEY_SPACE_END, mix64

__all__ = [
    "MigrationRange",
    "RangeTable",
    "ReshardLedger",
    "ledger_path",
    "plan_reshard",
    "reshard_metrics",
    "ring_ranges",
    "route_keys",
]

#: cutover states, in lifecycle order; the ledger enforces the order
STATES = ("pending", "dual_write", "flipped", "retired")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}

#: states at/after which the NEW owner answers reads
_FLIPPED_CODE = _STATE_CODE["flipped"]
_DUAL_CODE = _STATE_CODE["dual_write"]


@dataclass(frozen=True)
class MigrationRange:
    """One ring arc changing hands: positions ``[lo, hi)`` (mixed/ring
    space, Python ints — ``hi`` may be 2**64) move shard ``src`` → ``dst``."""

    lo: int
    hi: int
    src: int
    dst: int


def _ring_points(num_shards: int, vnodes: int):
    """The fleet's ring for ``num_shards`` — lazy import so this module
    stays importable without the transport stack behind ``fleet``."""
    from advanced_scrapper_tpu.index.fleet import _ring

    return _ring(num_shards, vnodes)


def ring_ranges(num_shards: int, vnodes: int = 64) -> list[tuple[int, int, int]]:
    """The ring as disjoint sorted ``(lo, hi, owner)`` covering exactly
    ``[0, 2**64)`` — the interval form of ``ring_assign`` (the property
    tests assert the two agree on every key)."""
    pts, owner = _ring_points(num_shards, vnodes)
    out: list[tuple[int, int, int]] = []
    lo = 0
    for i in range(len(pts)):
        hi = int(pts[i]) + 1  # searchsorted-left: a point owns positions ≤ it
        if hi > lo:
            out.append((lo, hi, int(owner[i])))
        lo = hi
    # the wrap arc past the last point belongs to the first point's owner
    if lo < KEY_SPACE_END:
        out.append((lo, KEY_SPACE_END, int(owner[0])))
    return out


def _owner_at(pts, owner, pos: int) -> int:
    ix = int(np.searchsorted(pts, np.uint64(pos)))
    return int(owner[ix % len(pts)])


def plan_reshard(
    old_n: int, new_n: int, vnodes: int = 64
) -> tuple[MigrationRange, ...]:
    """Diff ring(``old_n``) against ring(``new_n``): the disjoint sorted
    arcs whose owner changes, coalesced.  Every position outside the
    returned ranges has the SAME owner under both rings — the router
    never needs a special case for them."""
    if old_n < 1 or new_n < 1:
        raise ValueError(f"shard counts must be ≥1 (got {old_n}→{new_n})")
    if old_n == new_n:
        return ()
    pts_o, own_o = _ring_points(old_n, vnodes)
    pts_n, own_n = _ring_points(new_n, vnodes)
    bounds = sorted(
        {0, KEY_SPACE_END}
        | {int(p) + 1 for p in pts_o}
        | {int(p) + 1 for p in pts_n}
    )
    out: list[MigrationRange] = []
    for lo, hi in zip(bounds, bounds[1:]):
        o = _owner_at(pts_o, own_o, lo)
        n = _owner_at(pts_n, own_n, lo)
        if o == n:
            continue
        if out and out[-1].hi == lo and (out[-1].src, out[-1].dst) == (o, n):
            out[-1] = MigrationRange(out[-1].lo, hi, o, n)
        else:
            out.append(MigrationRange(lo, hi, o, n))
    return tuple(out)


class RangeTable:
    """The migrating arcs + their live cutover states, as parallel numpy
    arrays so the fleet's per-batch routing is one ``searchsorted`` —
    rebuilt (cheap: one small array) on every state change."""

    def __init__(self, ranges: list[dict]):
        # each entry: {"lo", "hi", "src", "dst", "state"}
        self.ranges = [dict(r) for r in ranges]
        self._lock = threading.Lock()
        self._rebuild()

    def _rebuild(self) -> None:
        n = len(self.ranges)
        self._los = np.array([r["lo"] for r in self.ranges], np.uint64)
        # hi may be 2**64 (unrepresentable): compare against hi-1 inclusive
        self._his1 = np.array([r["hi"] - 1 for r in self.ranges], np.uint64)
        self._srcs = np.array([r["src"] for r in self.ranges], np.int32)
        self._dsts = np.array([r["dst"] for r in self.ranges], np.int32)
        self._codes = np.array(
            [_STATE_CODE[r["state"]] for r in self.ranges], np.int8
        ) if n else np.zeros(0, np.int8)

    def set_state(self, i: int, state: str) -> None:
        with self._lock:
            self.ranges[i]["state"] = state
            self._codes[i] = _STATE_CODE[state]

    def state(self, i: int) -> str:
        return self.ranges[i]["state"]

    def locate(self, pos: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """``(range index, in-a-migrating-arc mask)`` per ring position."""
        if not len(self.ranges):
            z = np.zeros(pos.shape, np.int64)
            return z, np.zeros(pos.shape, bool)
        ix = np.searchsorted(self._los, pos, side="right").astype(np.int64) - 1
        valid = ix >= 0
        ixc = np.clip(ix, 0, len(self.ranges) - 1)
        valid &= pos <= self._his1[ixc]
        return ixc, valid

    def counts(self) -> dict[str, int]:
        out = {s: 0 for s in STATES}
        for r in self.ranges:
            out[r["state"]] += 1
        return out


def route_keys(
    keys: np.ndarray, table: RangeTable, old_n: int, new_n: int, vnodes: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-key ``(read/write owner, dual-write target)`` while a reshard
    is live.  The dual target is ``-1`` outside a dual-write window; the
    primary is the OLD owner until an arc flips, the NEW owner after —
    exactly the lifecycle table in the module docstring."""
    from advanced_scrapper_tpu.index.fleet import ring_assign

    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    old = ring_assign(keys, old_n, vnodes)
    if not len(table.ranges):
        return old, np.full(keys.shape, -1, np.int32)
    new = ring_assign(keys, new_n, vnodes)
    ix, valid = table.locate(mix64(keys))
    codes = table._codes[ix]
    primary = np.where(valid & (codes >= _FLIPPED_CODE), new, old).astype(np.int32)
    dual = np.where(valid & (codes == _DUAL_CODE), new, -1).astype(np.int32)
    return primary, dual


# -- the migration WAL -------------------------------------------------------

def ledger_path(spill_dir: str, space: str) -> str:
    """The migration WAL's home — under the client's spill dir (the one
    durable directory the CLIENT owns), named ``reshard-wal-*`` so the
    chaos plane's WAL targeting (``only=wal-`` / ``only=reshard-wal``)
    reaches it."""
    return os.path.join(spill_dir, f"reshard-wal-{space}.json")


class ReshardLedger:
    """The durable cutover state machine: one JSON document, every write
    an ``atomic_replace`` — the commit point of every flip.

    A crash mid-write leaves the PREVIOUS whole document (that is what
    atomic replace means), so resume always reads a consistent snapshot:
    flipped/retired ranges are kept (their data is verified on the new
    owner — the flip write happened strictly after the digest match),
    everything else is voided back to ``pending`` and re-migrated.
    """

    VERSION = 1

    def __init__(self, path: str, doc: dict, fs=None):
        from advanced_scrapper_tpu.storage.fsio import default_fs

        self.path = path
        self.fs = fs or default_fs()
        self.doc = doc

    # -- construction --------------------------------------------------------

    @classmethod
    def create(
        cls,
        path: str,
        *,
        old_n: int,
        new_n: int,
        vnodes: int,
        old_spec: str,
        new_spec: str,
        space: str,
        ranges,
        fs=None,
    ) -> "ReshardLedger":
        doc = {
            "version": cls.VERSION,
            "phase": "active",
            "old_n": int(old_n),
            "new_n": int(new_n),
            "vnodes": int(vnodes),
            "old_spec": old_spec,
            "new_spec": new_spec,
            "space": space,
            "voids": 0,
            "ranges": [
                {
                    "lo": int(r.lo), "hi": int(r.hi),
                    "src": int(r.src), "dst": int(r.dst),
                    "state": "pending",
                }
                for r in ranges
            ],
        }
        led = cls(path, doc, fs=fs)
        led.save()
        return led

    @classmethod
    def load(cls, path: str, fs=None) -> "ReshardLedger | None":
        from advanced_scrapper_tpu.storage.fsio import default_fs

        fs = fs or default_fs()
        if not fs.exists(path):
            return None
        with fs.open(path, "rb") as fh:
            doc = json.loads(fh.read().decode("utf-8"))
        if int(doc.get("version", 0)) != cls.VERSION:
            raise ValueError(
                f"{path}: unknown reshard ledger version {doc.get('version')}"
            )
        for r in doc.get("ranges", []):
            if r.get("state") not in _STATE_CODE:
                raise ValueError(
                    f"{path}: unrepresentable range state {r.get('state')!r}"
                )
        return cls(path, doc, fs=fs)

    def save(self) -> None:
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        atomic_replace(
            self.path,
            json.dumps(self.doc, indent=1).encode("utf-8"),
            fs=self.fs,
        )

    # -- state machine -------------------------------------------------------

    @property
    def phase(self) -> str:
        return self.doc["phase"]

    @property
    def ranges(self) -> list[dict]:
        return self.doc["ranges"]

    def mark(self, i: int, state: str) -> None:
        """Advance range ``i``; forward-only except the resume void
        (``dual_write → pending``), which goes through :meth:`void_unflipped`."""
        cur = self.doc["ranges"][i]["state"]
        if _STATE_CODE[state] <= _STATE_CODE[cur]:
            raise ValueError(
                f"range {i}: cannot move {cur!r} → {state!r} (forward-only)"
            )
        self.doc["ranges"][i]["state"] = state
        self.save()

    def void_unflipped(self) -> int:
        """The resume discipline: any range caught mid-window (armed but
        never sealed by a flip write) never happened.  Returns how many
        were voided; one durable write covers them all."""
        n = 0
        for r in self.doc["ranges"]:
            if r["state"] == "dual_write":
                r["state"] = "pending"
                n += 1
        if n:
            self.doc["voids"] = int(self.doc.get("voids", 0)) + n
            self.save()
        return n

    def finish(self) -> None:
        self.doc["phase"] = "done"
        self.save()

    def all_retired(self) -> bool:
        return all(r["state"] == "retired" for r in self.doc["ranges"])


# -- telemetry ---------------------------------------------------------------

def reshard_metrics(fleet_id: str) -> dict:
    """The ``astpu_reshard_*`` handles for one fleet client.  Counters are
    always-on (the crashsweep verifier reads them from a child report
    without the telemetry plane enabled); the page histograms are gated
    like every other volume series."""
    from advanced_scrapper_tpu.obs import telemetry

    reg = telemetry.REGISTRY
    return {
        "pages": reg.counter(
            "astpu_reshard_pages_total",
            "migration pages streamed src → dst",
            always=True, fleet=fleet_id,
        ),
        "postings": reg.counter(
            "astpu_reshard_postings_moved_total",
            "semantic postings migrated to their new owner",
            always=True, fleet=fleet_id,
        ),
        "flips": reg.counter(
            "astpu_reshard_flips_total",
            "ranges atomically cut over to their new owner",
            always=True, fleet=fleet_id,
        ),
        "voids": reg.counter(
            "astpu_reshard_voids_total",
            "ranges voided back to pending on resume (crash mid-window)",
            always=True, fleet=fleet_id,
        ),
        "dual": reg.counter(
            "astpu_reshard_dual_writes_total",
            "insert batches dual-applied to a range's next owner",
            always=True, fleet=fleet_id,
        ),
        "retries": reg.counter(
            "astpu_reshard_digest_retries_total",
            "cutover digest mismatches that forced a re-stream",
            always=True, fleet=fleet_id,
        ),
        "page_s": telemetry.histogram(
            "astpu_reshard_page_seconds",
            "one migration page: fetch + push + ack",
            fleet=fleet_id,
        ),
        "page_b": telemetry.histogram(
            "astpu_reshard_page_bytes",
            "payload bytes per migration page",
            fleet=fleet_id,
        ),
    }


def register_state_gauges(fleet_id: str, table: RangeTable) -> None:
    """One ``astpu_reshard_range_state`` gauge per migrating arc (state
    code 0–3 per the lifecycle table) plus the in-flight total — gated,
    weakly owned by the table, so a finished reshard stops exporting."""
    from advanced_scrapper_tpu.obs import telemetry

    for i in range(len(table.ranges)):
        telemetry.REGISTRY.gauge_fn(
            "astpu_reshard_range_state",
            lambda t, i=i: int(t._codes[i]),
            owner=table, fleet=fleet_id, range=str(i),
            help="cutover state per range: 0 pending, 1 dual_write, "
                 "2 flipped, 3 retired",
        )
    telemetry.REGISTRY.gauge_fn(
        "astpu_reshard_ranges_pending",
        lambda t: int((t._codes < _FLIPPED_CODE).sum()),
        owner=table, fleet=fleet_id,
        help="ranges not yet flipped to their new owner",
    )
