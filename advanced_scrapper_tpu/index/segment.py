"""Immutable sorted segment files with per-segment Bloom filters.

A segment is one generation of postings cut from the WAL: keys sorted
ascending (ties broken by doc id), written ONCE through
``storage.fsio.atomic_write`` — so a segment on disk is whole-or-absent by
construction, never torn — and never modified again.  Readers keep only the
per-segment Bloom filter (and a 64-byte header) resident; the sorted key and
doc arrays are ``np.memmap``'d, so probing an N-posting history costs RAM
proportional to the *Bloom* sizing (~10 bits/posting at the 1% default),
not to the postings themselves — the LSHBloom memory contract, with
attribution kept because the postings still exist on disk.

Probe path per batch: Bloom membership first (a negative — the common case
for fresh content — never touches the posting arrays), then a vectorised
``searchsorted`` equal-range scan for the surviving keys.  A Bloom positive
that finds no posting is an *observed* false positive and is counted, so
``/status`` shows the live observed-FP ratio next to the predicted one.

Layout (little-endian)::

    magic 8s | version u32 | count u64 | bloom_bits u64 | bloom_hashes u32 |
    bloom_seed u32 | header crc32 u32 | pad → 64 B
    bloom words u64[bloom_bits/64]
    keys u64[count]          (sorted)
    docs u64[count]          (parallel to keys)
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from advanced_scrapper_tpu.storage.fsio import atomic_write, default_fs
from advanced_scrapper_tpu.utils.bloom import BloomBandIndex

__all__ = ["Segment", "write_segment", "bloom_for_count"]

_MAGIC = b"ASTPUSEG"
_VERSION = 1
_HEAD = struct.Struct("<8sIQQIII")  # magic, ver, count, bits, hashes, seed, crc
HEADER_LEN = 64


def bloom_for_count(count: int, *, seed: int = 0, row_fp: float = 0.01) -> BloomBandIndex:
    """Per-segment filter sized for ``count`` keys at ~``row_fp`` — a
    single-band :class:`BloomBandIndex`, so the sizing/saturation math is
    the one already measured in ``tools/soak_bloom.py``."""
    return BloomBandIndex.for_capacity(
        max(1, count), num_bands=1, row_fp=row_fp, seed=seed
    )


def _header_bytes(count: int, bloom: BloomBandIndex) -> bytes:
    body = _HEAD.pack(
        _MAGIC, _VERSION, count, bloom.bits, bloom.num_hashes, bloom.seed, 0
    )
    crc = zlib.crc32(body)
    packed = _HEAD.pack(
        _MAGIC, _VERSION, count, bloom.bits, bloom.num_hashes, bloom.seed, crc
    )
    return packed + b"\0" * (HEADER_LEN - len(packed))


def write_segment(
    path: str,
    keys: np.ndarray,
    docs: np.ndarray,
    *,
    seed: int = 0,
    fs=None,
) -> None:
    """Sort + deduplicate the posting batch and atomically persist it.

    Duplicate ``(key, doc)`` pairs collapse to one; multiple docs per key
    survive (compaction tombstones all but the first-seen later).  The
    rename inside :func:`atomic_write` is the commit point — a crash at any
    earlier byte leaves no segment at ``path``.
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
    docs = np.ascontiguousarray(docs, dtype=np.uint64).ravel()
    if keys.shape != docs.shape:
        raise ValueError(f"keys/docs length mismatch: {keys.shape} vs {docs.shape}")
    order = np.lexsort((docs, keys))
    keys, docs = keys[order], docs[order]
    if keys.size:
        fresh = np.empty(keys.size, bool)
        fresh[0] = True
        fresh[1:] = (keys[1:] != keys[:-1]) | (docs[1:] != docs[:-1])
        keys, docs = keys[fresh], docs[fresh]
    bloom = bloom_for_count(int(keys.size), seed=seed)
    if keys.size:
        bloom.add_batch(keys[:, None])

    def writer(fh):
        fh.write(_header_bytes(int(keys.size), bloom))
        fh.write(bloom._words.tobytes())
        fh.write(keys.tobytes())
        fh.write(docs.tobytes())

    atomic_write(path, writer, fs=fs)


class Segment:
    """Reader over one immutable segment file.

    Resident memory: header + Bloom words.  ``keys``/``docs`` are memmaps —
    the OS pages postings in only for the (rare) Bloom-positive probes.
    """

    def __init__(self, path: str, fs=None):
        self.path = path
        fs = fs or default_fs()
        with fs.open(path, "rb") as fh:
            head = fh.read(HEADER_LEN)
            if len(head) < HEADER_LEN:
                raise ValueError(f"segment {path}: truncated header")
            magic, ver, count, bits, hashes, seed, crc = _HEAD.unpack_from(head)
            if magic != _MAGIC or ver != _VERSION:
                raise ValueError(f"segment {path}: bad magic/version")
            expect = zlib.crc32(
                _HEAD.pack(_MAGIC, ver, count, bits, hashes, seed, 0)
            )
            if crc != expect:
                raise ValueError(f"segment {path}: header checksum mismatch")
            words = np.frombuffer(fh.read(bits // 8), dtype=np.uint64)
            if words.size != bits // 64:
                raise ValueError(f"segment {path}: truncated bloom plane")
        self.count = int(count)
        self.bloom = BloomBandIndex(1, bits=int(bits), num_hashes=int(hashes), seed=int(seed))
        self.bloom.restore(words.reshape(1, -1).copy(), self.count, 64)
        expected = HEADER_LEN + bits // 8 + 16 * self.count
        actual = fs.size(path)
        if actual != expected:
            raise ValueError(
                f"segment {path}: size {actual} != expected {expected}"
            )
        keys_off = HEADER_LEN + bits // 8
        if self.count:
            self.keys = np.memmap(path, dtype=np.uint64, mode="r",
                                  offset=keys_off, shape=(self.count,))
            self.docs = np.memmap(path, dtype=np.uint64, mode="r",
                                  offset=keys_off + 8 * self.count,
                                  shape=(self.count,))
        else:
            self.keys = np.zeros((0,), np.uint64)
            self.docs = np.zeros((0,), np.uint64)
        # observed-FP accounting (scraped as a ratio by the store's gauges)
        self.bloom_hits = 0
        self.bloom_false = 0

    @property
    def resident_bytes(self) -> int:
        return self.bloom.memory_bytes + HEADER_LEN

    @property
    def file_bytes(self) -> int:
        return HEADER_LEN + self.bloom.memory_bytes + 16 * self.count

    def probe(self, flat_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """``(query_rows, doc_ids)`` posting matches for ``uint64[n]`` keys.

        Bloom-negative keys never touch the posting memmaps; a key may
        match several postings (several doc ids), all are returned.
        """
        flat_keys = np.asarray(flat_keys, dtype=np.uint64).ravel()
        if self.count == 0 or flat_keys.size == 0:
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        maybe = self.bloom.contains_batch(flat_keys[:, None])
        rows = np.flatnonzero(maybe)
        if rows.size == 0:
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        q = flat_keys[rows]
        lo = np.searchsorted(self.keys, q, side="left")
        hi = np.searchsorted(self.keys, q, side="right")
        n_match = hi - lo
        hit = n_match > 0
        self.bloom_hits += int(rows.size)
        self.bloom_false += int(rows.size - hit.sum())
        if not hit.any():
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        rows, lo, n_match = rows[hit], lo[hit], n_match[hit]
        out_rows = np.repeat(rows, n_match)
        flat_ix = np.concatenate(
            [np.arange(l, l + n) for l, n in zip(lo.tolist(), n_match.tolist())]
        )
        return out_rows.astype(np.int64), np.asarray(self.docs[flat_ix])

    def arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """Materialised ``(keys, docs)`` copies — compaction/verification
        input, not a probe path."""
        return np.asarray(self.keys).copy(), np.asarray(self.docs).copy()

    def close(self) -> None:
        # memmaps release on GC; drop references eagerly so Windows-style
        # holders (and ChaosFs tests) can delete files after compaction
        self.keys = self.docs = None
