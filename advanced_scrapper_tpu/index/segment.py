"""Immutable sorted segment files with per-segment Bloom filters.

A segment is one generation of postings cut from the WAL: keys sorted
ascending (ties broken by doc id), written ONCE through
``storage.fsio.atomic_write`` — so a segment on disk is whole-or-absent by
construction, never torn — and never modified again.  Readers keep only the
per-segment Bloom filter (and a 64-byte header) resident; the sorted key and
doc arrays are ``np.memmap``'d, so probing an N-posting history costs RAM
proportional to the *Bloom* sizing (~10 bits/posting at the 1% default),
not to the postings themselves — the LSHBloom memory contract, with
attribution kept because the postings still exist on disk.

Probe path per batch: Bloom membership first (a negative — the common case
for fresh content — never touches the posting arrays), then a vectorised
``searchsorted`` equal-range scan for the surviving keys.  A Bloom positive
that finds no posting is an *observed* false positive and is counted, so
``/status`` shows the live observed-FP ratio next to the predicted one.

**Integrity (format v2).**  A memmap'd body that lives for months is
exposed to silent bit rot: the OS pages bytes straight off disk with no
checksum between the medium and the probe answer.  v2 therefore carries a
per-block CRC32 table over all three body planes (Bloom words, keys,
docs), block-aligned PER PLANE so verification never crosses a memmap
boundary:

- the Bloom plane is verified **eagerly at open** (it is fully read into
  RAM then anyway);
- key/doc blocks are verified **lazily on first probe touch** (the
  equal-range rows a probe actually reads), each block at most once per
  open — the steady-state probe cost is unchanged;
- :meth:`Segment.verify_all` verifies **every** block plus the
  whole-file digest — the scrub / fsck path.

A failed check raises :class:`SegmentCorruption`; the store quarantines
the segment (PR 1 ``.quarantine`` sidecar convention) instead of serving
poison.  v1 segments (no CRC table) remain transparently readable —
lazy/eager verification simply has nothing to check beyond structure.

Layout v2 (little-endian)::

    magic 8s | version u32 | count u64 | bloom_bits u64 | bloom_hashes u32 |
    bloom_seed u32 | block_bytes u32 | table crc32 u32 | header crc32 u32 |
    pad → 64 B
    bloom words u64[bloom_bits/64]
    keys u64[count]          (sorted)
    docs u64[count]          (parallel to keys)
    crc table u32[nb(bloom) + nb(keys) + nb(docs)]   (per-plane blocks)

v1 ends after the docs plane and carries no ``block_bytes``/table fields.
"""

from __future__ import annotations

import hashlib
import struct
import zlib

import numpy as np

from advanced_scrapper_tpu.storage.fsio import atomic_write, default_fs
from advanced_scrapper_tpu.utils.bloom import BloomBandIndex

__all__ = [
    "Segment",
    "SegmentCorruption",
    "write_segment",
    "bloom_for_count",
    "file_digest",
]

_MAGIC = b"ASTPUSEG"
VERSION = 2
_HEAD_V1 = struct.Struct("<8sIQQIII")    # magic, ver, count, bits, hashes, seed, crc
_HEAD_V2 = struct.Struct("<8sIQQIIIII")  # ... + block_bytes, table_crc, crc
_HEAD_PREFIX = struct.Struct("<8sI")     # magic, ver — shared by both
HEADER_LEN = 64
#: CRC block granularity: 64 KiB = 8192 postings per key/doc block — small
#: enough that a lazy probe-touch verify is microseconds, large enough
#: that the table is ~0.006% of the body
BLOCK_BYTES = 1 << 16

_DIGEST_CHUNK = 1 << 20


class SegmentCorruption(Exception):
    """A segment failed an integrity check (block CRC, header CRC, table
    CRC or whole-file digest).  The store's response is quarantine —
    never serving an answer derived from the corrupt bytes."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"segment {path}: {detail}")
        self.path = path
        self.detail = detail


def bloom_for_count(count: int, *, seed: int = 0, row_fp: float = 0.01) -> BloomBandIndex:
    """Per-segment filter sized for ``count`` keys at ~``row_fp`` — a
    single-band :class:`BloomBandIndex`, so the sizing/saturation math is
    the one already measured in ``tools/soak_bloom.py``."""
    return BloomBandIndex.for_capacity(
        max(1, count), num_bands=1, row_fp=row_fp, seed=seed
    )


def _n_blocks(nbytes: int, block: int) -> int:
    return (nbytes + block - 1) // block


def _plane_crcs(buf, block: int) -> np.ndarray:
    """``uint32[ceil(len/block)]`` CRC32 per block of one body plane."""
    mv = memoryview(buf)
    out = np.empty(_n_blocks(len(mv), block), np.uint32)
    for i in range(out.size):
        out[i] = zlib.crc32(mv[i * block : (i + 1) * block])
    return out


def file_digest(path: str, fs=None) -> str:
    """Whole-file blake2b-128 hex digest — the manifest-recorded identity
    of a segment (and of snapshot artifacts)."""
    fs = fs or default_fs()
    h = hashlib.blake2b(digest_size=16)
    with fs.open(path, "rb") as fh:
        while True:
            chunk = fh.read(_DIGEST_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _header_bytes_v2(
    count: int, bloom: BloomBandIndex, block: int, table_crc: int
) -> bytes:
    body = _HEAD_V2.pack(
        _MAGIC, VERSION, count, bloom.bits, bloom.num_hashes, bloom.seed,
        block, table_crc, 0,
    )
    crc = zlib.crc32(body)
    packed = _HEAD_V2.pack(
        _MAGIC, VERSION, count, bloom.bits, bloom.num_hashes, bloom.seed,
        block, table_crc, crc,
    )
    return packed + b"\0" * (HEADER_LEN - len(packed))


def _header_bytes_v1(count: int, bloom: BloomBandIndex) -> bytes:
    body = _HEAD_V1.pack(
        _MAGIC, 1, count, bloom.bits, bloom.num_hashes, bloom.seed, 0
    )
    crc = zlib.crc32(body)
    packed = _HEAD_V1.pack(
        _MAGIC, 1, count, bloom.bits, bloom.num_hashes, bloom.seed, crc
    )
    return packed + b"\0" * (HEADER_LEN - len(packed))


def write_segment(
    path: str,
    keys: np.ndarray,
    docs: np.ndarray,
    *,
    seed: int = 0,
    fs=None,
    version: int = VERSION,
    block_bytes: int = BLOCK_BYTES,
) -> str:
    """Sort + deduplicate the posting batch and atomically persist it;
    returns the whole-file digest (hex) for the caller's manifest.

    Duplicate ``(key, doc)`` pairs collapse to one; multiple docs per key
    survive (compaction tombstones all but the first-seen later).  The
    rename inside :func:`atomic_write` is the commit point — a crash at any
    earlier byte leaves no segment at ``path``.

    ``version=1`` writes the legacy CRC-less format — kept ONLY so the
    transparent-read compatibility tests can fabricate pre-v2 segments;
    production writers always emit v2.
    """
    if version not in (1, VERSION):
        raise ValueError(f"unknown segment version {version}")
    keys = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
    docs = np.ascontiguousarray(docs, dtype=np.uint64).ravel()
    if keys.shape != docs.shape:
        raise ValueError(f"keys/docs length mismatch: {keys.shape} vs {docs.shape}")
    order = np.lexsort((docs, keys))
    keys, docs = keys[order], docs[order]
    if keys.size:
        fresh = np.empty(keys.size, bool)
        fresh[0] = True
        fresh[1:] = (keys[1:] != keys[:-1]) | (docs[1:] != docs[:-1])
        keys, docs = keys[fresh], docs[fresh]
    bloom = bloom_for_count(int(keys.size), seed=seed)
    if keys.size:
        bloom.add_batch(keys[:, None])

    bloom_b = bloom._words.tobytes()
    keys_b = keys.tobytes()
    docs_b = docs.tobytes()
    if version == 1:
        parts = [_header_bytes_v1(int(keys.size), bloom), bloom_b, keys_b, docs_b]
    else:
        table = np.concatenate(
            [
                _plane_crcs(bloom_b, block_bytes),
                _plane_crcs(keys_b, block_bytes),
                _plane_crcs(docs_b, block_bytes),
            ]
        )
        table_b = table.tobytes()
        parts = [
            _header_bytes_v2(
                int(keys.size), bloom, block_bytes, zlib.crc32(table_b)
            ),
            bloom_b, keys_b, docs_b, table_b,
        ]
    digest = hashlib.blake2b(digest_size=16)
    for p in parts:
        digest.update(p)

    def writer(fh):
        for p in parts:
            fh.write(p)

    atomic_write(path, writer, fs=fs)
    return digest.hexdigest()


class Segment:
    """Reader over one immutable segment file.

    Resident memory: header + Bloom words + (v2) the CRC table and two
    verified-block bitmasks.  ``keys``/``docs`` are memmaps — the OS pages
    postings in only for the (rare) Bloom-positive probes, and each
    touched block is CRC-verified once before its bytes influence an
    answer.
    """

    def __init__(self, path: str, fs=None):
        self.path = path
        fs = fs or default_fs()
        with fs.open(path, "rb") as fh:
            head = fh.read(HEADER_LEN)
            if len(head) < HEADER_LEN:
                raise ValueError(f"segment {path}: truncated header")
            magic, ver = _HEAD_PREFIX.unpack_from(head)
            if magic != _MAGIC or ver not in (1, VERSION):
                raise ValueError(f"segment {path}: bad magic/version")
            self.version = int(ver)
            if ver == 1:
                _m, _v, count, bits, hashes, seed, crc = _HEAD_V1.unpack_from(head)
                expect = zlib.crc32(
                    _HEAD_V1.pack(_MAGIC, 1, count, bits, hashes, seed, 0)
                )
                block, table_crc = 0, 0
            else:
                (_m, _v, count, bits, hashes, seed, block, table_crc,
                 crc) = _HEAD_V2.unpack_from(head)
                expect = zlib.crc32(
                    _HEAD_V2.pack(
                        _MAGIC, VERSION, count, bits, hashes, seed, block,
                        table_crc, 0,
                    )
                )
            if crc != expect:
                raise SegmentCorruption(path, "header checksum mismatch")
            bloom_bytes = fh.read(bits // 8)
            if len(bloom_bytes) != bits // 8:
                raise ValueError(f"segment {path}: truncated bloom plane")
            words = np.frombuffer(bloom_bytes, dtype=np.uint64)
            self.count = int(count)
            self.block_bytes = int(block)
            nb_bloom = _n_blocks(bits // 8, block) if block else 0
            nb_keys = _n_blocks(8 * self.count, block) if block else 0
            nb_docs = nb_keys
            expected = HEADER_LEN + bits // 8 + 16 * self.count
            if ver == VERSION:
                expected += 4 * (nb_bloom + nb_keys + nb_docs)
            actual = fs.size(path)
            if actual != expected:
                raise ValueError(
                    f"segment {path}: size {actual} != expected {expected}"
                )
            if ver == VERSION:
                fh.seek(HEADER_LEN + bits // 8 + 16 * self.count)
                table_b = fh.read(4 * (nb_bloom + nb_keys + nb_docs))
                if zlib.crc32(table_b) != table_crc:
                    raise SegmentCorruption(path, "CRC table checksum mismatch")
                table = np.frombuffer(table_b, np.uint32)
                self._crc_bloom = table[:nb_bloom]
                self._crc_keys = table[nb_bloom : nb_bloom + nb_keys]
                self._crc_docs = table[nb_bloom + nb_keys :]
                # the bloom plane is fully resident from here on: verify it
                # now, while we still hold the exact bytes that were read
                got = _plane_crcs(bloom_bytes, block)
                bad = np.flatnonzero(got != self._crc_bloom)
                if bad.size:
                    raise SegmentCorruption(
                        path, f"bloom plane CRC mismatch in block {int(bad[0])}"
                    )
            else:
                self._crc_keys = self._crc_docs = None
        self.bloom = BloomBandIndex(1, bits=int(bits), num_hashes=int(hashes), seed=int(seed))
        self.bloom.restore(words.reshape(1, -1).copy(), self.count, 64)
        keys_off = HEADER_LEN + bits // 8
        if self.count:
            self.keys = np.memmap(path, dtype=np.uint64, mode="r",
                                  offset=keys_off, shape=(self.count,))
            self.docs = np.memmap(path, dtype=np.uint64, mode="r",
                                  offset=keys_off + 8 * self.count,
                                  shape=(self.count,))
        else:
            self.keys = np.zeros((0,), np.uint64)
            self.docs = np.zeros((0,), np.uint64)
        # lazy verification state: block i verified ⇔ _ok_*[i].  Races are
        # benign (two probes re-verify the same immutable bytes), so no
        # lock — verification is idempotent and monotone.
        if self.version == VERSION and self.count:
            self._ok_keys = np.zeros(len(self._crc_keys), bool)
            self._ok_docs = np.zeros(len(self._crc_docs), bool)
        else:
            self._ok_keys = self._ok_docs = None
        # observed-FP accounting (scraped as a ratio by the store's gauges)
        self.bloom_hits = 0
        self.bloom_false = 0

    @property
    def resident_bytes(self) -> int:
        table = 0
        if self.version == VERSION and self._crc_keys is not None:
            table = 4 * (
                len(self._crc_bloom) + len(self._crc_keys) + len(self._crc_docs)
            )
        return self.bloom.memory_bytes + HEADER_LEN + table

    @property
    def file_bytes(self) -> int:
        base = HEADER_LEN + self.bloom.memory_bytes + 16 * self.count
        if self.version == VERSION:
            nb = _n_blocks(8 * self.count, self.block_bytes) if self.count else 0
            base += 4 * (
                _n_blocks(self.bloom.memory_bytes, self.block_bytes) + 2 * nb
            )
        return base

    # -- integrity ---------------------------------------------------------

    def _verify_blocks(self, plane: np.ndarray, crcs, ok, b0: int, b1: int):
        """Verify blocks ``[b0, b1)`` of one posting plane against the CRC
        table (skipping already-verified ones); raises on mismatch."""
        rows_per = self.block_bytes // 8
        for b in range(b0, b1):
            if ok[b]:
                continue
            lo = b * rows_per
            hi = min(self.count, lo + rows_per)
            got = zlib.crc32(np.ascontiguousarray(plane[lo:hi]).tobytes())
            if got != int(crcs[b]):
                raise SegmentCorruption(
                    self.path,
                    f"block CRC mismatch ({'keys' if crcs is self._crc_keys else 'docs'} "
                    f"block {b}, rows {lo}..{hi})",
                )
            ok[b] = True

    def _verify_rows(self, lo: int, hi: int) -> None:
        """Lazy probe-path check: CRC-verify the key and doc blocks holding
        rows ``[lo, hi)``, each block at most once per open."""
        if self._ok_keys is None or hi <= lo:
            return
        rows_per = self.block_bytes // 8
        b0, b1 = lo // rows_per, (max(lo, hi - 1) // rows_per) + 1
        self._verify_blocks(self.keys, self._crc_keys, self._ok_keys, b0, b1)
        self._verify_blocks(self.docs, self._crc_docs, self._ok_docs, b0, b1)

    def verify_all(self, fs=None) -> str:
        """Eagerly verify EVERY block of every plane (scrub / fsck path)
        and return the whole-file digest; raises :class:`SegmentCorruption`
        on the first mismatch.

        The bloom plane is re-read from DISK here (the resident copy was
        verified at open; scrub's job is the bytes as they are now)."""
        fs = fs or default_fs()
        if self.version == VERSION:
            with fs.open(self.path, "rb") as fh:
                fh.seek(HEADER_LEN)
                bloom_bytes = fh.read(self.bloom.memory_bytes)
            got = _plane_crcs(bloom_bytes, self.block_bytes)
            bad = np.flatnonzero(got != self._crc_bloom)
            if bad.size:
                raise SegmentCorruption(
                    self.path, f"bloom plane CRC mismatch in block {int(bad[0])}"
                )
            if self.count:
                # full sweep: force re-verification of every block (bit rot
                # can land AFTER a block was lazily verified)
                self._ok_keys[:] = False
                self._ok_docs[:] = False
                self._verify_blocks(
                    self.keys, self._crc_keys, self._ok_keys,
                    0, len(self._crc_keys),
                )
                self._verify_blocks(
                    self.docs, self._crc_docs, self._ok_docs,
                    0, len(self._crc_docs),
                )
        return file_digest(self.path, fs=fs)

    def probe(self, flat_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """``(query_rows, doc_ids)`` posting matches for ``uint64[n]`` keys.

        Bloom-negative keys never touch the posting memmaps; a key may
        match several postings (several doc ids), all are returned.  Every
        posting row consulted for an answer sits in a CRC-verified block
        (v2) — corruption raises :class:`SegmentCorruption` instead of
        flowing into an attribution.
        """
        flat_keys = np.asarray(flat_keys, dtype=np.uint64).ravel()
        if self.count == 0 or flat_keys.size == 0:
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        maybe = self.bloom.contains_batch(flat_keys[:, None])
        rows = np.flatnonzero(maybe)
        if rows.size == 0:
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        q = flat_keys[rows]
        lo = np.searchsorted(self.keys, q, side="left")
        hi = np.searchsorted(self.keys, q, side="right")
        n_match = hi - lo
        hit = n_match > 0
        self.bloom_hits += int(rows.size)
        self.bloom_false += int(rows.size - hit.sum())
        if self._ok_keys is not None:
            # a bloom-positive MISS is either an honest Bloom false
            # positive (~1%) or a key whose stored bytes rotted out of its
            # sort position — verify the blocks AROUND the landing point
            # so a flipped key raises here instead of silently reading as
            # "never posted".  Rows [lo-1, lo] suffice for a SINGLE
            # rotted row: binary search over a sorted array with one
            # out-of-place element converges adjacent to it (an inflated
            # row sends the search left until it closes AT the rot; a
            # deflated row sends it right until it closes just past it),
            # so the corrupt row is always in a verified block.  Multi-row
            # rot within one file is the scrub/digest pass's job.
            for l in lo[~hit].tolist():
                r0 = max(l - 1, 0)
                r1 = min(max(l, 0) + 1, self.count)
                self._verify_rows(r0, r1)
        if not hit.any():
            e = np.zeros((0,), np.int64)
            return e, e.astype(np.uint64)
        rows, lo, n_match = rows[hit], lo[hit], n_match[hit]
        for l, n in zip(lo.tolist(), n_match.tolist()):
            self._verify_rows(l, l + n)
        out_rows = np.repeat(rows, n_match)
        flat_ix = np.concatenate(
            [np.arange(l, l + n) for l, n in zip(lo.tolist(), n_match.tolist())]
        )
        return out_rows.astype(np.int64), np.asarray(self.docs[flat_ix])

    def arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """Materialised ``(keys, docs)`` copies — compaction/verification
        input, not a probe path."""
        return np.asarray(self.keys).copy(), np.asarray(self.docs).copy()

    def close(self) -> None:
        # memmaps release on GC; drop references eagerly so Windows-style
        # holders (and ChaosFs tests) can delete files after compaction
        self.keys = self.docs = None
