"""Write-ahead log of (band-key, doc-id) postings — torn-tail-safe.

The WAL is the durability floor of :class:`~.store.PersistentIndex`: every
posting batch is appended here *before* it enters the in-memory memtable, so
a crash at any instant loses at most the record that was mid-write — and
that record is dropped *whole* on replay (CRC framing), never half-applied.
Re-processing the document that produced it then converges: its postings
were either fully durable (the done-probe finds them) or fully absent (they
are appended again).

Framing: each append is ONE record ::

    magic u32 | n u32 | crc32 u32 | keys u64[n] | docs u64[n]

with the CRC over the payload (keys+docs bytes).  Replay walks records from
the start and stops at the first short / CRC-failing record — by
construction that can only be the tail left by a crashed writer.  A *failed*
append inside a live process (injected EIO / short write through the
``storage.fsio`` seam) truncates the file back to the pre-append offset so
later appends never sit behind a torn record mid-file; if even the truncate
fails the log marks itself broken and refuses further appends rather than
corrupt framing silently.

All I/O goes through the fsio seam, so ``ChaosFs`` torn-write / fsync /
crash faults apply to the WAL for free (the crashsweep ``pindex`` workload
kills inside these appends).
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from advanced_scrapper_tpu.storage.fsio import default_fs

__all__ = ["WriteAheadLog", "replay_wal"]

_MAGIC = 0xA51DC0DE
_HEADER = struct.Struct("<III")  # magic, n, crc32(payload)


def _payload(keys: np.ndarray, docs: np.ndarray) -> bytes:
    return keys.tobytes() + docs.tobytes()


class WriteAheadLog:
    """Append-only posting log for one index directory generation."""

    def __init__(self, path: str, fs=None):
        self.path = path
        self._fs = fs or default_fs()
        self._fh = self._fs.open(path, "ab")
        self._broken = False
        self.appended = 0  # postings appended through THIS handle

    def append(self, keys: np.ndarray, docs: np.ndarray) -> None:
        """Durably frame one posting batch; all-or-nothing on replay.

        On an injected/real write error the record is rolled back
        (truncate to the pre-append offset) so the log stays well-framed
        for subsequent appends; the caller must treat the batch as NOT
        persisted (and must not add it to the memtable).
        """
        if self._broken:
            raise OSError(f"write-ahead log {self.path} is broken; reopen the index")
        keys = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
        docs = np.ascontiguousarray(docs, dtype=np.uint64).ravel()
        if keys.shape != docs.shape:
            raise ValueError(f"keys/docs length mismatch: {keys.shape} vs {docs.shape}")
        if keys.size == 0:
            return
        payload = _payload(keys, docs)
        rec = _HEADER.pack(_MAGIC, keys.size, zlib.crc32(payload)) + payload
        start = self._fh.tell()
        try:
            self._fh.write(rec)
            self._fh.flush()
        except BaseException:
            # a SimulatedCrash propagates (the process is "dead" — disk
            # keeps the torn tail, exactly like SIGKILL); ordinary errors
            # roll the partial record back so framing survives
            try:
                self._fh.truncate(start)
                self._fh.seek(0, os.SEEK_END)
            except Exception:
                self._broken = True
            raise
        self.appended += keys.size

    def sync(self) -> None:
        """fsync the log (the checkpoint-cadence durability point)."""
        self._fh.flush()
        self._fs.fsync(self._fh)

    def close(self) -> None:
        try:
            self._fh.close()
        except Exception:
            pass


def replay_wal(path: str, fs=None) -> tuple[np.ndarray, np.ndarray, int]:
    """Recover every whole record: ``(keys u64[n], docs u64[n], valid_end)``.

    Stops at the first torn/corrupt record — the tail a crashed writer
    left — and returns everything before it, plus the byte offset where
    the valid prefix ends.  A writer REOPENING the log must truncate the
    file to ``valid_end`` first (``PersistentIndex`` does): appending in
    ``ab`` mode behind a torn record would leave every new record
    unreplayable forever, since replay can never walk past the garbage.
    A missing file is an empty log (the fresh-directory case).
    """
    fs = fs or default_fs()
    if not fs.exists(path):
        e = np.zeros((0,), np.uint64)
        return e, e, 0
    keys_parts: list[np.ndarray] = []
    docs_parts: list[np.ndarray] = []
    with fs.open(path, "rb") as fh:
        data = fh.read()
    off = 0
    while off + _HEADER.size <= len(data):
        magic, n, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC:
            break
        body_len = 16 * n  # u64 keys + u64 docs
        end = off + _HEADER.size + body_len
        if end > len(data):
            break  # short tail record
        payload = data[off + _HEADER.size : end]
        if zlib.crc32(payload) != crc:
            break  # torn mid-record
        keys_parts.append(np.frombuffer(payload, np.uint64, count=n))
        docs_parts.append(np.frombuffer(payload, np.uint64, count=n, offset=8 * n))
        off = end
    if not keys_parts:
        e = np.zeros((0,), np.uint64)
        return e, e, off
    return np.concatenate(keys_parts), np.concatenate(docs_parts), off
