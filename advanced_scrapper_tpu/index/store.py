"""The persistent corpus index: WAL → memtable → segments → compaction.

:class:`PersistentIndex` is the durable cross-run successor of every
session-local dedup index in the tree.  It stores ``(band-key → doc-id)``
postings for an evolving corpus with three properties the npz-checkpoint
model could not give:

- **incremental durability** — every posting batch is framed into a
  write-ahead log (:mod:`.wal`) through the ``storage.fsio`` seam *before*
  it becomes probe-able, so no save/load of the whole index ever happens
  and a crash at any byte loses at most one in-flight batch (which the
  producer re-derives on resume);
- **bounded resident memory** — postings live in immutable sorted segment
  files (:mod:`.segment`); only their per-segment Bloom filters stay in
  RAM, so probing a billion-posting history is a Bloom check plus a rare
  memmap'd binary search (the LSHBloom contract, with attribution);
- **crash-safe reorganisation** — segment cuts and compactions commit by
  atomically swapping ``manifest.json`` (the single source of truth for
  which files are live); every file not named by the manifest is an orphan
  from a crashed writer and is swept on open.

First-seen-wins attribution is encoded in doc-id order: doc ids are
allocated monotonically (persisted via the manifest, re-derived from the
WAL on crash), a probe returns the *minimum* doc id over all postings for
a key, and compaction tombstones every posting for a key except the
minimum — later postings are superseded by definition, because no probe
can ever prefer them.

Concurrency: one writer thread (insert/cut) + N probe threads + an
optional background compaction thread.  Mutable state (memtable, segment
list, manifest) is guarded by one lock; segment files themselves are
immutable, so the heavy merge work runs outside the lock.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from advanced_scrapper_tpu.index.segment import (
    Segment,
    SegmentCorruption,
    file_digest,
    write_segment,
)
from advanced_scrapper_tpu.index.wal import WriteAheadLog, replay_wal
from advanced_scrapper_tpu.storage.fsio import atomic_replace, default_fs

__all__ = ["PersistentIndex", "resolve_intra_batch"]

MANIFEST = "manifest.json"
DOCMAP = "docmap.log"

#: seconds an idle cached semantic state (repair/digest input) survives —
#: long enough to span one paged repair conversation, short enough that a
#: finished repair frees the arrays at the next checkpoint beat
SEMANTIC_CACHE_TTL_S = 60.0

NO_DOC = np.int64(-1)


def _wal_name(seq: int) -> str:
    return f"wal-{seq:08d}.log"


def _seg_name(seq: int) -> str:
    return f"seg-{seq:08d}.seg"


def resolve_intra_batch(
    keys: np.ndarray, doc_ids: np.ndarray, attr: np.ndarray
) -> np.ndarray:
    """First-seen-wins resolution WITHIN one batch, in place.

    ``attr`` is the cross-run attribution the index probe produced
    (``-1`` = no historical match); rows sharing a band key with an
    earlier still-fresh row of the same batch attribute to that row's doc
    id.  Kept (fresh) rows only ever become attribution targets — a dup
    row's id is never posted, so it must never be referenced.

    Shared verbatim by :meth:`PersistentIndex.check_and_add_batch` and
    the fleet client (``index/fleet.py``): the byte-equality of a sharded
    fleet against the single-node oracle rests on both running exactly
    this resolution between the probe and the insert.
    """
    B, nb = keys.shape
    # the pass only touches rows holding a key that occurs in MORE than
    # one row of the batch — any other row can neither match an earlier
    # row nor be matched by a later one, so the (ordered, kept-rows-only)
    # resolution loop runs over the shared minority
    uniq, counts = np.unique(keys, return_counts=True)
    kc = counts[np.searchsorted(uniq, keys.ravel())].reshape(B, nb)
    shared_rows = np.flatnonzero((kc > 1).any(axis=1))
    kept_keys: dict[int, int] = {}  # key → doc id of the first KEPT row
    for r in shared_rows.tolist():
        row = keys[r].tolist()
        if attr[r] < 0:
            for k in row:
                d = kept_keys.get(k)
                if d is not None:
                    attr[r] = d
                    break
        if attr[r] < 0:
            for k in row:
                kept_keys.setdefault(k, int(doc_ids[r]))
    return attr


class PersistentIndex:
    """A sharded log-structured (key → doc-id) posting index on disk."""

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        directory: str,
        *,
        cut_postings: int = 1 << 16,
        compact_segments: int = 8,
        compact_inline: bool = False,
        read_only: bool = False,
        fs=None,
    ):
        """Open (or create) the index at ``directory``.

        ``cut_postings`` — memtable postings that trigger a segment cut
        (the WAL/segment-cut cadence; the scraper maps its checkpoint knob
        here).  ``compact_segments`` — live-segment count that triggers
        compaction (0 disables); compaction runs on a daemon thread unless
        ``compact_inline`` (tests, and the crashsweep child, need the
        deterministic ordering).

        ``read_only`` — open for probing/inspection WITHOUT mutating the
        directory: no orphan sweep, no WAL tail repair, no append handle.
        The only safe way to open a directory a live writer may own (the
        offline ``lookup_names`` flow, the crashsweep safety checker) —
        a writable open would sweep the writer's pre-commit cut files out
        from under it.  Mutating calls raise.
        """
        self.dir = directory
        self.cut_postings = int(cut_postings)
        self.compact_segments = int(compact_segments)
        self.compact_inline = bool(compact_inline)
        self.read_only = bool(read_only)
        self._fs = fs or default_fs()
        self._lock = threading.RLock()
        self._compact_busy = threading.Lock()
        if not read_only:
            os.makedirs(directory, exist_ok=True)

        t0 = time.perf_counter()
        man = self._load_manifest()
        self._seg_seq = int(man.get("seg_seq", 0))
        self._wal_seq = int(man.get("wal_seq", 0))
        #: whole-file digest per live segment (manifest-recorded identity;
        #: pre-v2 manifests lack entries — scrub backfills them)
        self._digests: dict[str, str] = dict(man.get("digests", {}))
        self._segments: list[Segment] = []
        dirty_manifest = False
        for name in man.get("segments", []):
            path = os.path.join(directory, name)
            try:
                self._segments.append(Segment(path, fs=self._fs))
            except (FileNotFoundError, ValueError, SegmentCorruption) as e:
                # PR 1 torn-artifact philosophy: a segment that cannot be
                # opened because its BYTES are wrong (header-CRC
                # mismatch, truncation, bad magic, bit rot in the
                # resident planes) or is simply gone is quarantined —
                # sidecar + counter — and the index continues on the
                # surviving manifest instead of crashing the whole open.
                # Transient resource errors (EMFILE/ENOMEM/EINTR…) are
                # NOT corruption and propagate: quarantining a healthy
                # segment on fd pressure would permanently withdraw its
                # postings where a plain retry loses nothing.
                self._quarantine_segment_file(path, str(e))
                self._digests.pop(name, None)
                dirty_manifest = True
        if not read_only:
            self._sweep_orphans(
                {os.path.basename(s.path) for s in self._segments}
            )
        # WAL replay rebuilds the memtable; its doc ids also re-derive the
        # allocation high-water mark a crash may have kept out of the
        # manifest (manifest next_doc_id is only persisted at cut time)
        wal_path = os.path.join(directory, _wal_name(self._wal_seq))
        mk, md, wal_end = replay_wal(wal_path, fs=self._fs)
        self._mem_keys: list[np.ndarray] = [mk] if mk.size else []
        self._mem_docs: list[np.ndarray] = [md] if md.size else []
        self._mem_count = int(mk.size)
        self._mem_map: dict[int, int] = {}
        for k, d in zip(mk.tolist(), md.tolist()):
            prev = self._mem_map.get(k)
            if prev is None or d < prev:
                self._mem_map[k] = d
        self._next_doc_id = int(man.get("next_doc_id", 0))
        if md.size:
            self._next_doc_id = max(self._next_doc_id, int(md.max()) + 1)
        #: ring ranges (mixed space, [lo, hi) Python ints) this node has
        #: legitimately handed off to a new owner: physically present
        #: postings inside them are excluded from every semantic read
        #: (probe/dump/digest) and new inserts for them are dropped —
        #: logical tombstones, so replicas retired at different instants
        #: still digest-agree and fsck sees handoff, not loss
        self._handed_off: list[tuple[int, int]] = [
            (int(a), int(b)) for a, b in man.get("handed_off", [])
        ]
        #: active reshard fence ({"token": ...}) — snapshot tooling
        #: refuses to capture a node mid-cutover
        self._reshard_mark: dict | None = man.get("reshard") or None
        #: (state key, (keys, docs), warmed-at) — see semantic_items
        self._semantic_cache = None
        if read_only:
            self._wal = None
        else:
            self._repair_wal_tail(wal_path, wal_end)
            self._wal = WriteAheadLog(wal_path, fs=self._fs)
            if dirty_manifest:
                # commit the quarantine: the manifest must stop naming the
                # sidelined segment or every reopen re-quarantines a file
                # that is no longer there
                self._write_manifest()
        self.reopen_seconds = time.perf_counter() - t0
        self._instrument()
        if not read_only and os.environ.get("ASTPU_INDEX_SCRUB", "") not in ("", "0"):
            self.scrub()

    def _repair_wal_tail(self, wal_path: str, valid_end: int) -> None:
        """Truncate a torn WAL tail before reopening the appender: records
        appended in ``ab`` mode BEHIND torn garbage would be unreplayable
        forever (replay stops at the first bad frame), so every posting of
        the recovered session until the next cut would silently vanish on
        the following open."""
        if not self._fs.exists(wal_path):
            return
        if self._fs.size(wal_path) <= valid_end:
            return
        with self._fs.open(wal_path, "r+b") as fh:
            fh.truncate(valid_end)
        from advanced_scrapper_tpu.obs import telemetry

        telemetry.event_counter(
            "astpu_index_wal_torn_total",
            "torn WAL tails truncated at index open (crash artifacts)",
        ).inc()

    def _check_writable(self) -> None:
        if self.read_only:
            raise ValueError(
                f"index at {self.dir} was opened read_only; probing and "
                "lookup_names are allowed, mutation is not"
            )

    # -- manifest / recovery -------------------------------------------------

    def _load_manifest(self) -> dict:
        path = os.path.join(self.dir, MANIFEST)
        if not self._fs.exists(path):
            return {}
        with self._fs.open(path, "rb") as fh:
            man = json.loads(fh.read().decode("utf-8"))
        if int(man.get("version", 1)) != 1:
            raise ValueError(f"unknown index manifest version in {path}")
        return man

    def _manifest_dict(self) -> dict:
        names = [os.path.basename(s.path) for s in self._segments]
        man = {
            "version": 1,
            "seg_seq": self._seg_seq,
            "wal_seq": self._wal_seq,
            "segments": names,
            "next_doc_id": self._next_doc_id,
            # whole-file digests: the corruption detector of last resort
            # (scrub/fsck recompute and compare) and the snapshot tool's
            # transfer-verification source
            "digests": {n: self._digests[n] for n in names if n in self._digests},
        }
        if self._handed_off:
            man["handed_off"] = [[a, b] for a, b in self._handed_off]
        if self._reshard_mark:
            man["reshard"] = dict(self._reshard_mark)
        return man

    def _write_manifest(self) -> None:
        """Atomic commit point for every structural change (cut, compact,
        rotation): the swapped file names exactly the live segment set,
        the live WAL generation, the doc-id high-water mark and every
        segment's whole-file digest."""
        atomic_replace(
            os.path.join(self.dir, MANIFEST),
            json.dumps(self._manifest_dict(), indent=1).encode("utf-8"),
            fs=self._fs,
        )

    def _sweep_orphans(self, live_segments: set) -> None:
        """Delete files a crashed writer left that the manifest does not
        name: cut/compaction outputs whose commit never happened, and WAL
        generations superseded by a committed rotation.  Never touches the
        live WAL or live segments, so a sweep is always safe."""
        live_wal = _wal_name(self._wal_seq)
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            stale = (
                (name.endswith(".seg") and name not in live_segments)
                or (name.startswith("wal-") and name.endswith(".log")
                    and name != live_wal)
            )
            if stale:
                try:
                    self._fs.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    # -- integrity: quarantine & scrub ---------------------------------------

    def _quarantine_segment_file(self, path: str, reason: str) -> None:
        """Sideline one corrupt/torn segment FILE: rename to the PR 1
        ``.quarantine`` sidecar (evidence preserved for the operator,
        invisible to every reader pattern) and count it.  In read-only
        mode the file is left in place — the checker observes, never
        mutates — but the drop from the live set still counts."""
        moved = False
        if not self.read_only:
            try:
                if self._fs.exists(path):
                    self._fs.replace(path, path + ".quarantine")
                    moved = True
            except OSError:
                pass
        from advanced_scrapper_tpu.obs import telemetry, trace

        telemetry.event_counter(
            "astpu_quarantine_total",
            "crash artifacts quarantined, by kind",
            kind="segment",
        ).inc()
        trace.record(
            "event", "quarantine.segment", path=os.path.basename(path),
            reason=reason, moved=moved,
        )

    def _quarantine_live_segment(self, seg: Segment, reason: str) -> None:
        """Quarantine a segment that is currently serving: drop it from
        the live set, commit the shrunken manifest, THEN sideline the
        file.  Postings it held stop answering — wrong answers would be
        worse — until scrub/repair (or a replica) restores them."""
        name = os.path.basename(seg.path)
        with self._lock:
            if seg not in self._segments:
                return  # a racing probe already quarantined it
            self._segments = [s for s in self._segments if s is not seg]
            self._digests.pop(name, None)
            if not self.read_only:
                try:
                    self._write_manifest()
                except OSError:
                    pass  # reopen re-quarantines; the sidecar rename below
                    #       still stops this file from being served
        # like compaction's swap: the dropped ref keeps any racing probe
        # alive (POSIX rename semantics — the memmap outlives the name);
        # never Segment.close()d here, or a concurrent probe of the same
        # snapshot would read from released arrays
        self._quarantine_segment_file(seg.path, reason)

    def scrub(self) -> dict:
        """End-to-end corruption pass: eagerly verify every block CRC of
        every live segment plus its manifest-recorded whole-file digest.
        Corrupt segments are quarantined (never served again); segments
        predating digest records get their digest backfilled.  Returns a
        report dict; safe on a read-only open (observe, don't mutate).

        Callers: ``ASTPU_INDEX_SCRUB=1`` runs it at open, the shard
        server exposes it as the ``scrub`` RPC, ``tools/fsck_index.py``
        is the offline twin."""
        t0 = time.perf_counter()
        with self._lock:
            snapshot = list(self._segments)
        report: dict = {
            "dir": self.dir,
            "segments": len(snapshot),
            "corrupt": [],
            "backfilled_digests": 0,
        }
        backfilled = False
        for seg in snapshot:
            name = os.path.basename(seg.path)
            try:
                digest = seg.verify_all(fs=self._fs)
            except SegmentCorruption as e:
                report["corrupt"].append({"segment": name, "detail": e.detail})
                self._m_scrub_corrupt.inc()
                self._quarantine_live_segment(seg, e.detail)
                continue
            except OSError:
                # the file vanished under us: a racing compaction
                # superseded this snapshot entry (its postings live in
                # the merged segment, which a later scrub covers) — not
                # corruption, just a stale snapshot row
                with self._lock:
                    still_live = seg in self._segments
                if still_live:
                    raise
                continue
            with self._lock:
                want = self._digests.get(name)
                if want is None:
                    self._digests[name] = digest
                    report["backfilled_digests"] += 1
                    backfilled = True
            if want is not None and want != digest:
                detail = (
                    f"whole-file digest mismatch ({digest} != manifest "
                    f"{want})"
                )
                report["corrupt"].append({"segment": name, "detail": detail})
                self._m_scrub_corrupt.inc()
                self._quarantine_live_segment(seg, detail)
        if backfilled and not self.read_only:
            with self._lock:
                self._write_manifest()
        self._m_scrubs.inc()
        self._m_scrub_s.observe(time.perf_counter() - t0)
        report["ok"] = not report["corrupt"]
        return report

    def semantic_items(self) -> tuple[np.ndarray, np.ndarray]:
        """The index's SEMANTIC state: sorted unique keys + the minimum
        doc id each attributes to — the representation anti-entropy
        digests and repair transfers run over (compaction timing and
        posting multiplicity cancel out of it by construction).

        Cached on the structural state (segment set + memtable size): a
        repair conversation pages dozens of digest/fetch_range calls
        against one quiescent state, and each would otherwise re-sort
        every posting.  The cache is dropped on the next insert and aged
        out at checkpoint cadence (:data:`SEMANTIC_CACHE_TTL_S`) so a
        finished repair never pins the materialised state indefinitely.
        Callers must treat the arrays as read-only."""
        from advanced_scrapper_tpu.index.repair import semantic_min

        key = self._semantic_key()
        with self._lock:
            cached = self._semantic_cache
            if cached is not None and cached[0] == key:
                return cached[1]
        items = semantic_min(*self.dump_postings())
        with self._lock:
            # only cache if the state did not move under the computation
            # (else the arrays would be filed under a stale key)
            if self._semantic_key() == key:
                self._semantic_cache = (key, items, time.monotonic())
        return items

    def _semantic_key(self):
        with self._lock:
            return (
                self._seg_seq, self._wal_seq, self._mem_count,
                tuple(os.path.basename(s.path) for s in self._segments),
                tuple(self._handed_off),
            )

    def _age_semantic_cache(self) -> None:
        """Free the materialised semantic arrays once the repair
        conversation that warmed them has clearly ended."""
        with self._lock:
            cached = self._semantic_cache
            if (
                cached is not None
                and time.monotonic() - cached[2] > SEMANTIC_CACHE_TTL_S
            ):
                self._semantic_cache = None

    # -- snapshot ------------------------------------------------------------

    def snapshot_meta(self) -> dict:
        """Consistent-snapshot fence + pin: cut the memtable (after the
        cut the WAL generation is empty, so the durable state is exactly
        manifest + immutable segments), then name every live file with
        its size and digest.  The returned dict + the named files ARE the
        snapshot; ``tools/fleet_snapshot.py`` assembles them."""
        if not self.read_only:
            self.cut_segment()  # no-op on an empty memtable
        with self._lock:
            files = []
            for s in self._segments:
                name = os.path.basename(s.path)
                digest = self._digests.get(name)
                if digest is None:
                    digest = file_digest(s.path, fs=self._fs)
                    self._digests[name] = digest
                files.append(
                    {"name": name, "bytes": int(self._fs.size(s.path)),
                     "digest": digest}
                )
            docmap = os.path.join(self.dir, DOCMAP)
            if self._fs.exists(docmap):
                files.append(
                    {"name": DOCMAP, "bytes": int(self._fs.size(docmap)),
                     "digest": file_digest(docmap, fs=self._fs)}
                )
            return {"manifest": self._manifest_dict(), "files": files}

    def read_file(self, name: str, offset: int = 0, limit: int | None = None) -> bytes:
        """Paged raw read of one snapshot-named file (segment, docmap or
        the manifest itself) — the ``fetch_file`` RPC body.  ``name`` is
        a bare basename; path traversal is rejected."""
        if os.path.basename(name) != name or name.startswith("."):
            raise ValueError(f"bad snapshot file name {name!r}")
        with self._lock:
            live = {os.path.basename(s.path) for s in self._segments}
        if name not in live and name not in (MANIFEST, DOCMAP):
            raise ValueError(f"{name!r} is not a live snapshot file")
        with self._fs.open(os.path.join(self.dir, name), "rb") as fh:
            fh.seek(int(offset))
            return fh.read(-1 if limit is None else int(limit))

    # -- resharding: handed-off ranges + cutover fence -----------------------

    def retire_range(self, lo: int, hi: int) -> None:
        """Record that ring range ``[lo, hi)`` (mixed space) was handed
        off to a new owner: one atomic manifest write, idempotent, after
        which every semantic read excludes the range and inserts for it
        are dropped.  Logical — no postings are physically deleted (the
        next compaction naturally rewrites without them being special)."""
        from advanced_scrapper_tpu.index.repair import interval_add

        self._check_writable()
        with self._lock:
            merged = interval_add(self._handed_off, int(lo), int(hi))
            if merged == self._handed_off:
                return
            self._handed_off = merged
            self._semantic_cache = None
            self._write_manifest()

    def unretire_range(self, lo: int, hi: int) -> None:
        """Re-acquire ``[lo, hi)`` — the N→M→N round trip hands an arc
        back to a node that once retired it; from this write on, inserts
        for the range land again.  (Postings resident from BEFORE the
        original handoff become visible again too — strictly older
        attributions the incoming migration stream re-asserts, and the
        cutover digest gate verifies the merged state byte-for-byte
        before this node answers reads for the range.)"""
        from advanced_scrapper_tpu.index.repair import interval_sub

        self._check_writable()
        with self._lock:
            cut = interval_sub(self._handed_off, int(lo), int(hi))
            if cut == self._handed_off:
                return
            self._handed_off = cut
            self._semantic_cache = None
            self._write_manifest()

    def handed_off_ranges(self) -> list[tuple[int, int]]:
        with self._lock:
            return list(self._handed_off)

    def set_reshard_mark(self, token: str) -> None:
        """Fence: a reshard involving this node is in flight.  Snapshot
        tooling refuses (or waits out) marked nodes — a manifest-of-
        manifests captured across a half-flipped range would restore a
        fleet that disagrees with itself."""
        self._check_writable()
        with self._lock:
            self._reshard_mark = {"token": str(token)}
            self._write_manifest()

    def clear_reshard_mark(self) -> None:
        self._check_writable()
        with self._lock:
            if self._reshard_mark is None:
                return
            self._reshard_mark = None
            self._write_manifest()

    def reshard_mark(self) -> dict | None:
        with self._lock:
            return dict(self._reshard_mark) if self._reshard_mark else None

    # -- telemetry -----------------------------------------------------------

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        with PersistentIndex._seq_lock:
            iid = f"{PersistentIndex._seq}:{os.path.basename(self.dir) or 'index'}"
            PersistentIndex._seq += 1
        self._m_probe_rows = telemetry.counter(
            "astpu_index_probe_rows_total", "query rows probed", index=iid
        )
        self._m_probe_hits = telemetry.counter(
            "astpu_index_probe_hits_total", "query rows that found a candidate",
            index=iid,
        )
        self._m_postings = telemetry.counter(
            "astpu_index_postings_total", "postings appended (WAL-framed)",
            index=iid,
        )
        self._m_tombstoned = telemetry.counter(
            "astpu_index_tombstoned_total",
            "superseded postings dropped by compaction", index=iid,
        )
        self._m_cuts = telemetry.counter(
            "astpu_index_segment_cuts_total", "segments cut from the WAL",
            index=iid,
        )
        self._m_compact_s = telemetry.histogram(
            "astpu_index_compaction_seconds", "compaction wall clock", index=iid
        )
        self._m_cut_s = telemetry.histogram(
            "astpu_index_segment_cut_seconds", "segment-cut wall clock", index=iid
        )
        self._m_scrubs = telemetry.counter(
            "astpu_scrub_runs_total", "integrity scrub passes", index=iid
        )
        self._m_scrub_s = telemetry.histogram(
            "astpu_scrub_seconds", "scrub pass wall clock", index=iid
        )
        # always-on: silent corruption surfacing is exactly what an
        # operator audits in an incident, telemetry gate or not
        self._m_scrub_corrupt = telemetry.event_counter(
            "astpu_scrub_corrupt_segments_total",
            "segments failing block-CRC/digest verification (quarantined)",
            index=iid,
        )
        for name, fn, help in (
            ("astpu_index_segments", lambda s: len(s._segments),
             "live segment files"),
            ("astpu_index_segment_bytes", lambda s: sum(
                g.file_bytes for g in s._segments), "on-disk segment bytes"),
            ("astpu_index_wal_postings", lambda s: s._mem_count,
             "postings in the live WAL/memtable (not yet in a segment)"),
            ("astpu_index_resident_bytes", lambda s: s.resident_bytes(),
             "RAM held by the index (segment Blooms + memtable)"),
            ("astpu_index_next_doc_id", lambda s: s._next_doc_id,
             "doc-id allocation high-water mark"),
            ("astpu_index_bloom_observed_fp", lambda s: s.observed_fp_ratio(),
             "observed per-segment Bloom false-positive ratio"),
        ):
            telemetry.gauge_fn(name, fn, owner=self, help=help, index=iid)

    # -- sizing / introspection ----------------------------------------------

    def resident_bytes(self) -> int:
        """RAM the index holds: segment Blooms + memtable postings (the
        bounded-memory contract the two-session test asserts — NOT the
        on-disk posting bytes, which are memmap'd)."""
        with self._lock:
            seg = sum(s.resident_bytes for s in self._segments)
            # dict entry ≈ 2 boxed ints + slot; 64 B is a safe upper figure
            return seg + self._mem_count * 16 + len(self._mem_map) * 64

    def disk_postings_bytes(self) -> int:
        with self._lock:
            return sum(16 * s.count for s in self._segments) + 16 * self._mem_count

    def posting_count(self) -> int:
        """Live postings (segments + memtable) — the cheap gauge accessor
        (no resident/byte aggregation; one lock, one sum)."""
        with self._lock:
            return sum(s.count for s in self._segments) + self._mem_count

    def observed_fp_ratio(self) -> float:
        with self._lock:
            hits = sum(s.bloom_hits for s in self._segments)
            false = sum(s.bloom_false for s in self._segments)
        return false / hits if hits else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments),
                "segment_postings": sum(s.count for s in self._segments),
                "segment_bytes": sum(s.file_bytes for s in self._segments),
                "wal_postings": self._mem_count,
                "resident_bytes": self.resident_bytes(),
                "next_doc_id": self._next_doc_id,
                "observed_bloom_fp": self.observed_fp_ratio(),
            }

    def dump_postings(self) -> tuple[np.ndarray, np.ndarray]:
        """Every live posting ``(keys, docs)`` — verification surface for
        the crash sweep's zero-lost / zero-duplicated assertions.  Keys in
        handed-off ranges are excluded: they belong to another node now,
        and counting them here would read as duplication fleet-wide."""
        with self._lock:
            parts = [s.arrays() for s in self._segments]
            parts += [(k, d) for k, d in zip(self._mem_keys, self._mem_docs)]
            handed = list(self._handed_off)
        if not parts:
            e = np.zeros((0,), np.uint64)
            return e, e
        keys = np.concatenate([p[0] for p in parts])
        docs = np.concatenate([p[1] for p in parts])
        if handed and keys.size:
            from advanced_scrapper_tpu.index.repair import range_mask

            keep = ~range_mask(keys, handed)
            keys, docs = keys[keep], docs[keep]
        return keys, docs

    # -- doc-id allocation / attribution -------------------------------------

    def allocate_doc_ids(self, n: int) -> np.ndarray:
        """``uint64[n]`` monotonically increasing ids.  Durable high-water:
        every POSTED id raises ``next_doc_id`` (``insert_batch``), which
        re-derives from the WAL on crash and from the manifest after a
        cut; ids handed out but never posted anywhere may be reissued
        after a restart — by then nothing durable references them (a
        caller posting ids into SIBLING indexes must union the floors at
        open: :meth:`doc_id_floor` / :meth:`raise_doc_id_floor`)."""
        self._check_writable()
        with self._lock:
            start = self._next_doc_id
            self._next_doc_id += int(n)
        return np.arange(start, start + n, dtype=np.uint64)

    def doc_id_floor(self) -> int:
        """The smallest id this index would allocate next — ≥ every id it
        has durably seen (posted, or reserved via a committed manifest)."""
        with self._lock:
            return self._next_doc_id

    def raise_doc_id_floor(self, floor: int) -> None:
        """Never allocate below ``floor`` — the cross-sub-index union hook:
        a backend allocating from THIS index but posting those ids into a
        sibling index too must, at open, raise this floor to the sibling's
        (else a crash before this index saw the ids durably would reissue
        them, silently re-pointing the sibling's old attributions)."""
        with self._lock:
            self._next_doc_id = max(self._next_doc_id, int(floor))

    def log_names(self, doc_ids, names) -> None:
        """Best-effort ``doc-id → name`` sidecar (attribution for humans;
        the index itself never reads it).  Torn tails are tolerated by the
        reader, so a crash mid-append costs at most one mapping line."""
        self._check_writable()
        lines = "".join(
            f"{int(d)}\t{str(n)}\n" for d, n in zip(doc_ids, names)
        ).encode("utf-8")
        try:
            with self._fs.open(os.path.join(self.dir, DOCMAP), "ab") as fh:
                fh.write(lines)
        except OSError:
            from advanced_scrapper_tpu.obs import telemetry

            telemetry.event_counter(
                "astpu_index_docmap_errors_total",
                "docmap sidecar appends that failed (attribution-only loss)",
            ).inc()

    def lookup_names(self, doc_ids) -> dict[int, str]:
        """Resolve doc ids from the sidecar (offline/operator path: O(file))."""
        want = {int(d) for d in doc_ids}
        out: dict[int, str] = {}
        path = os.path.join(self.dir, DOCMAP)
        if not self._fs.exists(path):
            return out
        with self._fs.open(path, "rb") as fh:
            data = fh.read()
        for line in data.split(b"\n")[:-1]:  # unterminated tail = torn, skip
            did, _, name = line.partition(b"\t")
            try:
                i = int(did)
            except ValueError:
                continue
            if i in want and i not in out:  # first-seen mapping wins
                out[i] = name.decode("utf-8", "replace")
        return out

    # -- core API ------------------------------------------------------------

    def insert_batch(self, keys: np.ndarray, docs: np.ndarray) -> None:
        """Durably append postings; they become probe-able only after the
        WAL framed them (all-or-nothing per call), then cut a segment if
        the memtable crossed the cadence threshold."""
        self._check_writable()
        keys = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
        docs = np.ascontiguousarray(docs, dtype=np.uint64).ravel()
        if keys.size and self._handed_off:
            # keys this node handed off are another owner's now — dropping
            # them makes a late retry/replay harmless and keeps retired
            # replicas digest-identical
            from advanced_scrapper_tpu.index.repair import range_mask

            with self._lock:
                handed = list(self._handed_off)
            keep = ~range_mask(keys, handed)
            if not keep.all():
                keys, docs = keys[keep], docs[keep]
        if keys.size == 0:
            return
        with self._lock:
            self._wal.append(keys, docs)  # raises ⇒ nothing became visible
            self._mem_keys.append(keys)
            self._mem_docs.append(docs)
            self._mem_count += keys.size
            mem = self._mem_map
            for k, d in zip(keys.tolist(), docs.tolist()):
                prev = mem.get(k)
                if prev is None or d < prev:
                    mem[k] = d
            # posted ids raise the allocation floor so it survives the cut
            # (manifest persists next_doc_id) and the crash (WAL replay)
            self._next_doc_id = max(self._next_doc_id, int(docs.max()) + 1)
            self._semantic_cache = None  # state moved; free the arrays
            self._m_postings.inc(keys.size)
            due = self._mem_count >= self.cut_postings
        if due:
            self.cut_segment()

    def probe_batch(self, keys: np.ndarray) -> np.ndarray:
        """``int64[B]`` earliest (minimum) candidate doc id per query row,
        ``-1`` where no band key of the row has ever been posted.

        ``keys`` is ``uint64[B, nb]`` (one row per document, one column per
        LSH band) or ``uint64[B]`` (single-key probes, e.g. url hashes).
        """
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim == 1:
            keys = keys[:, None]
        B = keys.shape[0]
        if B == 0:
            return np.zeros((0,), np.int64)
        flat = keys.ravel()
        best = np.full(flat.shape, np.iinfo(np.int64).max, np.int64)
        with self._lock:
            segments = list(self._segments)
            mem = self._mem_map
            if mem:
                # B×nb boxed dict lookups under the lock — fine at the
                # current cut cadence (memtable ≤ cut_postings); if the
                # memtable probe ever dominates a profile, mirror the
                # segment path: sorted parallel arrays + searchsorted
                mem_docs = np.fromiter(
                    (mem.get(k, -1) for k in flat.tolist()), np.int64, flat.size
                )
                hit = mem_docs >= 0
                best[hit] = mem_docs[hit]
        for seg in segments:
            try:
                rows, docs = seg.probe(flat)
            except SegmentCorruption as e:
                # bit rot surfaced on the probe path: quarantine instead
                # of serving an answer derived from the corrupt block (a
                # replica/scrub-repair restores the postings; a silently
                # wrong attribution would be forever)
                self._m_scrub_corrupt.inc()
                self._quarantine_live_segment(seg, e.detail)
                continue
            if rows.size:
                np.minimum.at(best, rows, docs.astype(np.int64))
        with self._lock:
            handed = list(self._handed_off)
        if handed:
            # a handed-off key must probe as absent HERE even though its
            # postings are still physically resident — the new owner
            # answers for it
            from advanced_scrapper_tpu.index.repair import range_mask

            best[range_mask(flat, handed)] = np.iinfo(np.int64).max
        best = best.reshape(B, -1).min(axis=1)
        out = np.where(best == np.iinfo(np.int64).max, NO_DOC, best)
        self._m_probe_rows.inc(B)
        self._m_probe_hits.inc(int((out >= 0).sum()))
        return out

    def check_and_add_batch(
        self, keys: np.ndarray, doc_ids: np.ndarray
    ) -> np.ndarray:
        """Stream step: per-row attribution (``int64[B]``, -1 = fresh),
        then insert the fresh rows' postings under their given doc ids.

        Cross-run membership via the index; intra-batch via true key
        equality against earlier KEPT rows of the batch (first-seen wins)
        — kept rows only, so every attribution references a doc id that
        is actually posted (and docmap-resolvable); a dup row's id is
        never posted and must never be an attribution target.
        """
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim == 1:
            keys = keys[:, None]
        doc_ids = np.ascontiguousarray(doc_ids, dtype=np.uint64).ravel()
        B, nb = keys.shape
        if B != doc_ids.size:
            raise ValueError(f"{B} key rows vs {doc_ids.size} doc ids")
        attr = resolve_intra_batch(
            keys, doc_ids, np.asarray(self.probe_batch(keys))
        )
        fresh = attr < 0
        if fresh.any():
            self.insert_batch(
                keys[fresh].ravel(), np.repeat(doc_ids[fresh], nb)
            )
        return attr

    # -- lifecycle: cut / compact / checkpoint / close ------------------------

    def cut_segment(self) -> bool:
        """Freeze the memtable into an immutable segment and rotate the WAL.

        Commit point: the manifest swap.  A crash before it leaves the old
        manifest + old WAL (the cut simply re-happens after reopen; the
        written segment — and the pre-opened next WAL generation — are
        orphans and are swept); a crash after it leaves the new manifest
        naming the new, already-created WAL generation, whose replay is
        empty; the postings live in the committed segment.  Either way:
        zero lost, zero duplicated.
        """
        self._check_writable()
        # The whole cut (sort, Bloom build, fsync'd write) holds the index
        # lock: correct but probe-blocking for its duration.  The
        # single-writer backends probe and insert from one thread, so
        # nothing stalls today; a multi-threaded prober would want the
        # compaction treatment (freeze the memtable, build outside the
        # lock, lock only for the manifest swap).
        with self._lock:
            if self._mem_count == 0:
                return False
            t0 = time.perf_counter()
            keys = np.concatenate(self._mem_keys)
            docs = np.concatenate(self._mem_docs)
            self._seg_seq += 1
            name = _seg_name(self._seg_seq)
            path = os.path.join(self.dir, name)
            digest = write_segment(path, keys, docs, seed=self._seg_seq, fs=self._fs)
            old_wal = self._wal
            old_wal_path = old_wal.path
            self._wal_seq += 1
            seg = Segment(path, fs=self._fs)
            self._segments.append(seg)
            self._digests[name] = digest
            try:
                # the new WAL generation opens BEFORE the commit: if the
                # manifest swap then commits, no fallible step remains —
                # appending to the superseded generation after a committed
                # rotation would be silently swept as an orphan on reopen
                new_wal = WriteAheadLog(
                    os.path.join(self.dir, _wal_name(self._wal_seq)),
                    fs=self._fs,
                )
                try:
                    self._write_manifest()  # ← the commit point
                except BaseException:
                    new_wal.close()
                    try:
                        self._fs.remove(new_wal.path)
                    except OSError:
                        pass
                    raise
            except BaseException:
                self._segments.pop()
                self._digests.pop(name, None)
                self._seg_seq -= 1
                self._wal_seq -= 1
                raise
            self._mem_keys, self._mem_docs = [], []
            self._mem_count = 0
            self._mem_map = {}
            self._wal = new_wal
            old_wal.close()
            try:
                self._fs.remove(old_wal_path)
            except OSError:
                pass  # superseded generation; swept on next open anyway
            self._m_cuts.inc()
            self._m_cut_s.observe(time.perf_counter() - t0)
            n_seg = len(self._segments)
        if self.compact_segments and n_seg >= self.compact_segments:
            if self.compact_inline:
                self.compact()
            else:
                threading.Thread(
                    target=self.compact, daemon=True,
                    name=f"astpu-index-compact-{os.path.basename(self.dir)}",
                ).start()
        return True

    def compact(self) -> bool:
        """Merge every live segment into one, tombstoning superseded
        postings (every posting for a key except its minimum doc id).

        The heavy merge runs outside the index lock against immutable
        files; the swap — manifest first, then the in-memory list — is
        atomic under the lock.  Segments cut concurrently with the merge
        are preserved (they are newer than the snapshot by construction).
        A crash during the manifest swap leaves the old manifest → old
        segment set, merged file swept as an orphan on reopen.
        """
        self._check_writable()
        if not self._compact_busy.acquire(blocking=False):
            return False  # a compaction is already running
        try:
            with self._lock:
                snapshot = list(self._segments)
                if len(snapshot) < 2:
                    return False
                self._seg_seq += 1
                name = _seg_name(self._seg_seq)
            t0 = time.perf_counter()
            pairs = [s.arrays() for s in snapshot]  # one materialisation each
            keys = np.concatenate([k for k, _d in pairs])
            docs = np.concatenate([d for _k, d in pairs])
            del pairs
            order = np.lexsort((docs, keys))
            keys, docs = keys[order], docs[order]
            first = np.empty(keys.size, bool)
            if keys.size:
                first[0] = True
                first[1:] = keys[1:] != keys[:-1]
            tombstoned = int(keys.size - first.sum())
            keys, docs = keys[first], docs[first]
            path = os.path.join(self.dir, name)
            digest = write_segment(path, keys, docs, seed=self._seg_seq, fs=self._fs)
            merged = Segment(path, fs=self._fs)
            old_names = {os.path.basename(s.path) for s in snapshot}
            with self._lock:
                fresh = [
                    s for s in self._segments
                    if os.path.basename(s.path) not in old_names
                ]
                self._segments = [merged] + fresh
                self._digests[name] = digest
                try:
                    self._write_manifest()  # ← the commit point
                except BaseException:
                    self._segments = snapshot + fresh
                    self._digests.pop(name, None)
                    raise
                for old in old_names:
                    self._digests.pop(old, None)
            # old segment files: dropped refs keep any racing probe alive
            # (POSIX unlink semantics); never Segment.close()d here
            for s in snapshot:
                try:
                    self._fs.remove(s.path)
                except OSError:
                    pass
            self._m_tombstoned.inc(tombstoned)
            self._m_compact_s.observe(time.perf_counter() - t0)
            return True
        finally:
            self._compact_busy.release()

    def checkpoint(self) -> None:
        """Durability point at the configured cadence: fsync the WAL, and
        cut a segment if the memtable crossed the cadence threshold."""
        self._check_writable()
        self._age_semantic_cache()
        with self._lock:
            self._wal.sync()
            due = self._mem_count >= self.cut_postings
        if due:
            self.cut_segment()

    def close(self) -> None:
        with self._lock:
            # terminal close (unlike compaction's swap, where racing
            # probes keep dropped segments alive): release the memmaps so
            # a close/reopen-heavy process never accumulates handles
            for s in self._segments:
                s.close()
            self._segments = []
            if self._wal is None:
                return
            try:
                self._wal.sync()
            except OSError:
                pass
            self._wal.close()

    def wipe(self) -> int:
        """Drop every posting — segments, memtable, WAL — in one committed
        step; returns the physical posting count dropped.

        The canary-space expiry primitive: a probe round's synthetic
        postings must vanish completely between rounds, but the doc-id
        high-water mark survives (``next_doc_id`` is monotone forever —
        reissuing an id would silently re-point any surviving external
        attribution, the :meth:`allocate_doc_ids` contract).

        Crash-safe the same way a cut is: the new (empty) WAL generation
        opens first, the manifest swap naming zero segments + the new
        generation is the commit point, and only then are the superseded
        files deleted — a crash before the commit reopens the old state
        intact, one after it sweeps the leftovers as orphans.  The docmap
        sidecar is dropped too (best-effort, like its writes): wiped
        postings must not leave attribution ghosts for explain queries.
        """
        self._check_writable()
        with self._lock:
            n = sum(s.count for s in self._segments) + self._mem_count
            old_segments = list(self._segments)
            old_digests = dict(self._digests)
            old_wal = self._wal
            old_wal_path = old_wal.path
            self._segments = []
            self._digests = {}
            self._wal_seq += 1
            try:
                new_wal = WriteAheadLog(
                    os.path.join(self.dir, _wal_name(self._wal_seq)),
                    fs=self._fs,
                )
                try:
                    self._write_manifest()  # ← the commit point
                except BaseException:
                    new_wal.close()
                    try:
                        self._fs.remove(new_wal.path)
                    except OSError:
                        pass
                    raise
            except BaseException:
                self._segments = old_segments
                self._digests = old_digests
                self._wal_seq -= 1
                raise
            self._mem_keys, self._mem_docs = [], []
            self._mem_count = 0
            self._mem_map = {}
            self._semantic_cache = None
            self._wal = new_wal
            old_wal.close()
            try:
                self._fs.remove(old_wal_path)
            except OSError:
                pass
            for s in old_segments:
                s.close()
                try:
                    self._fs.remove(s.path)
                except OSError:
                    pass
            docmap = os.path.join(self.dir, DOCMAP)
            try:
                if self._fs.exists(docmap):
                    self._fs.remove(docmap)
            except OSError:
                pass
            return n
