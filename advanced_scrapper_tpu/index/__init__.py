"""Persistent corpus index — durable, sharded, log-structured LSH postings.

Every other dedup structure in the tree is session-local (``NearDupEngine``
buckets, ``BloomBandIndex`` bit-planes, the backend's ``_kept_sigs`` lists);
the only durability was a monolithic whole-index npz checkpoint rewritten in
full on every save and reloaded in full on every resume.  This package is
the subsystem that replaces that: an incremental on-disk index of
``(band-key, doc-id)`` postings with bounded resident memory, so a restarted
run deduplicates incoming articles against the *entire historical corpus*
without ever holding that corpus — or its postings — in RAM (the
FOLD / LSHBloom shape: online fuzzy dedup over an evolving dataset).

Layering: this package may use ``storage.fsio`` (durability seam),
``utils.bloom`` (filter math) and ``obs`` (telemetry), but never
``pipeline`` — enforced by ``tools/lint_imports.py``.

- :mod:`.wal` — torn-tail-safe write-ahead log of posting batches.
- :mod:`.segment` — immutable sorted segment files with per-segment Blooms.
- :mod:`.store` — :class:`PersistentIndex`: WAL → memtable → segment cut →
  compaction, crash-safe via manifest swap.
"""

from advanced_scrapper_tpu.index.segment import (
    Segment,
    SegmentCorruption,
    file_digest,
    write_segment,
)
from advanced_scrapper_tpu.index.store import PersistentIndex
from advanced_scrapper_tpu.index.wal import WriteAheadLog, replay_wal

__all__ = [
    "PersistentIndex",
    "Segment",
    "SegmentCorruption",
    "file_digest",
    "write_segment",
    "WriteAheadLog",
    "replay_wal",
]
