"""Remote persistent-index shards: the server half of the index fleet.

:class:`IndexShardServer` hosts one or more :class:`~.store.PersistentIndex`
key spaces (``bands`` postings and the exact-``urls`` stage, mirroring the
local two-sub-index layout) behind the length-framed RPC plane
(``net/rpc.py``).  A shard owns a deterministic slice of the uint64
band-key space (the fleet client's consistent-hash ring decides which);
everything durable about it IS the wrapped ``PersistentIndex`` — WAL,
segments, manifest swap, crash recovery — so a SIGKILLed shard process
reopens with the exact guarantees the single-node crashsweep certified.

Retry idempotency has two nets:

- the transport replays cached responses for a duplicated request id
  (``RpcServer``), which covers retries within one server lifetime;
- ``insert`` is **semantically idempotent** across server restarts: the
  handler drops any posting ``(key, doc)`` whose key already attributes
  to a doc id ≤ ``doc``.  In the probe-before-insert protocols every
  caller uses (``check_and_add``, done markers, url postings) a key is
  only ever posted when absent, so the filter is a no-op on first
  delivery and exactly cancels a redelivery — a retried batch can never
  double-insert, and no future probe can tell the difference.

``python -m advanced_scrapper_tpu.index.remote --dir D --port 0
--port-file P`` serves a shard standalone (the crashsweep ``fleet``
workload SIGKILLs these mid-WAL-append); the module imports no JAX, so a
shard process is cheap to fork.
"""

from __future__ import annotations

import dataclasses
import os
import threading

import numpy as np

import advanced_scrapper_tpu.net.rpc as rpc  # the ONE allowed net import

from advanced_scrapper_tpu.index import repair as antientropy
from advanced_scrapper_tpu.index.store import PersistentIndex

__all__ = [
    "IndexShardServer",
    "NamespacePolicy",
    "NAMESPACE_POLICIES",
    "RemoteIndex",
    "namespace_policy",
    "paged_fetch_range",
    "serve_main",
]

DEFAULT_SPACES = ("bands", "urls")

#: reserved key-space name prefix for the ground-truth canary prober
#: (``obs/canary.py`` declares the same literal — it may not import this
#: layer).
CANARY_SPACE_PREFIX = "canary:"

#: reserved key-space name prefix for multi-tenant namespaces: the
#: service-layer gateway maps tenant ``t`` to ``tenant:t:<sub>`` spaces,
#: so a tenant's band keys cannot collide with another tenant's (or with
#: the shared ``bands``/``urls`` spaces) by construction.
TENANT_SPACE_PREFIX = "tenant:"


@dataclasses.dataclass(frozen=True)
class NamespacePolicy:
    """Declarative per-prefix key-space policy (the generalization of the
    canary plane's special-casing): which space names are provisioned on
    first touch, which the ``wipe`` RPC may drop, and which admission
    quota class the service layer bills them under.

    - ``auto_provision`` — spaces under the prefix materialize server-side
      on first touch (the prober / a new tenant needs a live fleet to
      answer without every deployment pre-declaring it); real spaces stay
      declaration-only, so a typo'd space name fails instead of silently
      shadowing the intended postings.
    - ``wipe_allowed`` — the ``wipe`` RPC drops postings only inside
      prefixes that declare it (canary expiry between probe rounds,
      tenant offboarding); a stray wipe aimed at a real space is refused
      server-side AND client-side.
    - ``quota_class`` — the admission class the front-door gateway uses
      when stacking per-namespace token buckets (informational at this
      layer: the index plane never imports runtime/).
    """

    prefix: str
    auto_provision: bool
    wipe_allowed: bool
    quota_class: str


#: longest-prefix-match table; the ``""`` entry is the catch-all for
#: declared real spaces (``bands``/``urls``/reshard targets): never
#: auto-provisioned, never wipeable.
NAMESPACE_POLICIES: tuple[NamespacePolicy, ...] = (
    NamespacePolicy(CANARY_SPACE_PREFIX, True, True, "canary"),
    NamespacePolicy(TENANT_SPACE_PREFIX, True, True, "tenant"),
    NamespacePolicy("", False, False, "system"),
)


def namespace_policy(space: str) -> NamespacePolicy:
    """The policy governing ``space``: longest matching prefix wins."""
    best = None
    for pol in NAMESPACE_POLICIES:
        if space.startswith(pol.prefix):
            if best is None or len(pol.prefix) > len(best.prefix):
                best = pol
    assert best is not None  # the "" catch-all always matches
    return best


class IndexShardServer:
    """One fleet shard: N persistent-index key spaces behind one RPC port."""

    def __init__(
        self,
        directory: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        spaces=DEFAULT_SPACES,
        cut_postings: int = 1 << 16,
        compact_segments: int = 8,
        compact_inline: bool = False,
        max_frame: int = rpc.DEFAULT_MAX_FRAME,
        frame_deadline: float = 30.0,
        name: str = "",
        status_port: int | None = None,
        max_inflight_inserts: int = 32,
        insert_rate: float = 0.0,
        ladder=None,
    ):
        """``status_port`` mirrors the lease server's observability
        sidecar: a small HTTP server beside the RPC socket serving ``GET
        /metrics`` + ``/status`` (0 = ephemeral port, None = only when
        telemetry is enabled) — the per-process endpoint the fleet
        metrics collector (``obs/collector.py``) scrapes.

        ``max_inflight_inserts`` bounds concurrently executing write
        handlers (``insert``/``check_and_add``): request number N+1 gets
        a counted ``RpcOverloaded`` reject with a retry-after hint
        instead of a thread and a WAL contention slot — the shard sheds
        instead of wedging (0 disables).  ``insert_rate`` adds a
        token-bucket rate cap on the same methods (writes/s; 0 = none).
        Probes, health pings and the control surface are never gated:
        an overloaded shard stays readable and provably alive.
        ``ladder`` (optional
        :class:`~advanced_scrapper_tpu.runtime.admission.DegradationLadder`)
        receives the admission pressure signal, so sustained write
        pressure walks the declared brownout steps."""
        self.dir = directory
        self.name = name or os.path.basename(directory.rstrip("/")) or "shard"
        self._status_port = status_port
        self.status_server = None
        self._lock = threading.Lock()
        self._stopped = False
        self.admission = None
        if max_inflight_inserts > 0 or insert_rate > 0:
            from advanced_scrapper_tpu.runtime.admission import (
                AdmissionController,
                DegradationLadder,
            )

            if ladder is None:
                # every admission-bounded shard exports a live
                # astpu_degraded_step series (the SLO engine's brownout
                # signal).  ONE step only: the shard's sole brownout
                # lever is shedding low-priority work — declaring the
                # engine steps (shrink_window/skip_rerank/fewer_bands)
                # here would emit phantom transitions for degradations
                # a shard cannot perform, and delay shed_low behind
                # three inert dwell climbs
                from advanced_scrapper_tpu.runtime.admission import (
                    LadderStep,
                )

                ladder = DegradationLadder(
                    [LadderStep("shed_low", 0.98, 0.75)],
                    name=f"shard:{self.name}",
                )
            from advanced_scrapper_tpu.runtime.admission import (
                PRIORITY_NORMAL,
            )

            self.admission = AdmissionController(
                rate=insert_rate,
                max_inflight=max_inflight_inserts,
                ladder=ladder,
                # gated writes arrive at NORMAL priority (no per-method
                # mapping here), so the shed step must shed AT normal or
                # it would be a declared lever that moves nothing —
                # under sustained ≥98% write pressure the shard refuses
                # ALL writes outright (reads/pings untouched) until
                # pressure calms below the exit threshold
                shed_at=PRIORITY_NORMAL,
                name=f"shard:{self.name}",
            )
        # saved for lazily provisioning canary: spaces with the same
        # durability knobs the declared spaces got
        self._index_kw = dict(
            cut_postings=cut_postings,
            compact_segments=compact_segments,
            compact_inline=compact_inline,
        )
        self.indexes: dict[str, PersistentIndex] = {
            sp: PersistentIndex(os.path.join(directory, sp), **self._index_kw)
            for sp in spaces
        }
        self.server = rpc.RpcServer(
            {
                "probe": self._h_probe,
                "insert": self._h_insert,
                "check_and_add": self._h_check_and_add,
                "allocate": self._h_allocate,
                "log_names": self._h_log_names,
                "floor": self._h_floor,
                "stats": self._h_stats,
                "dump": self._h_dump,
                "checkpoint": self._h_checkpoint,
                # the self-healing plane: anti-entropy digests + range
                # streaming, on-demand corruption scrub, and the
                # snapshot/fetch pair tools/fleet_snapshot.py drives
                "digest": self._h_digest,
                "fetch_range": self._h_fetch_range,
                "scrub": self._h_scrub,
                "snapshot": self._h_snapshot,
                "fetch_file": self._h_fetch_file,
                # the reshard control plane: handed-off range marks + the
                # mid-cutover fence (index/reshard.py drives these through
                # the fleet client's two-phase cutover)
                "retire_range": self._h_retire_range,
                "unretire_range": self._h_unretire_range,
                "reshard_mark": self._h_reshard_mark,
                # canary-space expiry (restricted to the canary: prefix)
                "wipe": self._h_wipe,
            },
            host=host,
            port=port,
            max_frame=max_frame,
            frame_deadline=frame_deadline,
            name=f"shard:{self.name}",
            admission=self.admission,
            # ONLY the write plane is gated: probes must keep answering
            # under a write storm (reads are cheap and the fleet's
            # byte-equality depends on them), and the control surface
            # (floor/stats/checkpoint) is how operators see the overload
            admission_methods=frozenset({"insert", "check_and_add"}),
        )

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> "IndexShardServer":
        self.server.start()
        from advanced_scrapper_tpu.obs import telemetry, trace

        # the shard announces itself in the flight-recorder ring: a chaos
        # dump harvested centrally must NAME the dead shard, not just its
        # pid (obs/collector.py reads this event out of the sidecar)
        trace.record(
            "event", "shard.serve", shard=self.name, port=self.server.port
        )
        if self._status_port is not None or telemetry.enabled():
            self.status_server = telemetry.StatusServer(
                port=self._status_port or 0,
                name=f"shard-{self.name}",
                extra_status=lambda: {
                    "shard": self.name,
                    "spaces": {
                        sp: idx.stats() for sp, idx in self.indexes.items()
                    },
                },
            ).start()
        return self

    def stop(self) -> None:
        """Idempotent: tests stop a 'killed' node and sweep everything
        again in teardown."""
        self.server.stop()
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            for idx in self.indexes.values():
                idx.close()

    def _space(self, header: dict) -> PersistentIndex:
        sp = header.get("space", "bands")
        try:
            return self.indexes[sp]
        except KeyError:
            pass
        if namespace_policy(sp).auto_provision:
            # policy-declared prefixes (canary probe rounds, tenant
            # namespaces) are provisioned on first touch; real spaces
            # stay declaration-only — a typo'd space name must fail, not
            # silently shadow the intended postings.
            with self._lock:
                idx = self.indexes.get(sp)
                if idx is None and not self._stopped:
                    idx = PersistentIndex(
                        os.path.join(self.dir, sp), **self._index_kw
                    )
                    self.indexes[sp] = idx
            if idx is not None:
                return idx
        raise KeyError(
            f"shard {self.name} hosts {sorted(self.indexes)}, not {sp!r}"
        )

    # -- handlers ----------------------------------------------------------

    def _h_probe(self, header, arrays):
        (keys,) = arrays
        docs = self._space(header).probe_batch(np.asarray(keys, np.uint64))
        return {}, [np.asarray(docs, np.int64)]

    def _h_insert(self, header, arrays):
        keys, docs = arrays
        idx = self._space(header)
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        docs = np.ascontiguousarray(docs, np.uint64).ravel()
        with self._lock:
            # semantic idempotency (see module docstring): drop postings
            # already superseded-or-equal, so a redelivered batch — same
            # request after a crash-reopen wiped the transport cache —
            # applies zero times instead of twice
            attr = np.asarray(idx.probe_batch(keys))
            fresh = (attr < 0) | (attr.astype(np.int64) > docs.astype(np.int64))
            if fresh.any():
                idx.insert_batch(keys[fresh], docs[fresh])
        return {"applied": int(fresh.sum()), "skipped": int((~fresh).sum())}

    def _h_check_and_add(self, header, arrays):
        keys, doc_ids = arrays
        idx = self._space(header)
        with self._lock:
            attr = idx.check_and_add_batch(
                np.asarray(keys, np.uint64), np.asarray(doc_ids, np.uint64)
            )
        return {}, [np.asarray(attr, np.int64)]

    def _h_allocate(self, header, arrays):
        idx = self._space(header)
        n = int(header["n"])
        floor = int(header.get("floor", 0))
        with self._lock:
            if floor:
                idx.raise_doc_id_floor(floor)
            ids = idx.allocate_doc_ids(n)
        return {}, [ids]

    def _h_log_names(self, header, arrays):
        (ids,) = arrays
        self._space(header).log_names(
            np.asarray(ids, np.uint64).tolist(), header.get("names", [])
        )
        return {}

    def _h_floor(self, header, arrays):
        return {"floor": int(self._space(header).doc_id_floor())}

    def _h_stats(self, header, arrays):
        return {
            "shard": self.name,
            "spaces": {sp: idx.stats() for sp, idx in self.indexes.items()},
        }

    def _h_dump(self, header, arrays):
        """Paged: a shard past ~4M postings must never build a response
        frame the client's own cap forces it to refuse."""
        keys, docs = self._space(header).dump_postings()
        total = int(keys.size)
        off = int(header.get("offset", 0))
        limit = header.get("limit")
        if limit is not None:
            hi = off + int(limit)
            keys, docs = keys[off:hi], docs[off:hi]
        elif off:
            keys, docs = keys[off:], docs[off:]
        return {"total": total}, [keys, docs]

    def _h_checkpoint(self, header, arrays):
        for idx in self.indexes.values():
            idx.checkpoint()
        return {}

    # -- self-healing plane ------------------------------------------------

    def _h_digest(self, header, arrays):
        """Bucketed key-space digest of the SEMANTIC state — the
        anti-entropy comparison unit (``index/repair.py``).

        ``mixed`` mode buckets by the key's RING POSITION (``mix64``) and
        restricts to positions in ``[lo, hi)`` when given — the reshard
        cutover's digest gate compares one migrating ring arc between its
        old and new owner this way (raw-key bucketing could never name a
        ring arc: ring position decorrelates the two spaces by design)."""
        idx = self._space(header)
        bits = int(header.get("bits", antientropy.DEFAULT_BITS))
        keys, docs = idx.semantic_items()
        if header.get("mixed"):
            pos = antientropy.mix64(keys)
            if "lo" in header:
                lo, hi = int(header["lo"]), int(header["hi"])
                m = pos >= np.uint64(lo)
                if hi < antientropy.KEY_SPACE_END:
                    m &= pos < np.uint64(hi)
                keys, docs, pos = keys[m], docs[m], pos[m]
            dig, cnt = antientropy.bucket_digests(keys, docs, bits, positions=pos)
        else:
            dig, cnt = antientropy.bucket_digests(keys, docs, bits)
        return {"bits": bits}, [dig, cnt]

    def _h_fetch_range(self, header, arrays):
        """Semantic ``(key, min-doc)`` pairs with key in ``[lo, hi)`` —
        paged like ``dump`` so a hot bucket can never build a frame past
        the cap.  ``hi`` may be 2**64 (the last bucket's open end).
        ``mixed`` selects by ring position instead of raw key — the
        migration stream's page source."""
        idx = self._space(header)
        lo, hi = int(header["lo"]), int(header["hi"])
        keys, docs = idx.semantic_items()
        if header.get("mixed"):
            pos = antientropy.mix64(keys)
            m = pos >= np.uint64(lo)
            if hi < antientropy.KEY_SPACE_END:
                m &= pos < np.uint64(hi)
            keys, docs = keys[m], docs[m]
        else:
            # semantic keys are sorted: the [lo, hi) slice is two binary
            # searches, not a full-array mask per page
            i0 = int(np.searchsorted(keys, np.uint64(lo), side="left"))
            i1 = (
                keys.size
                if hi >= antientropy.KEY_SPACE_END
                else int(np.searchsorted(keys, np.uint64(hi), side="left"))
            )
            keys, docs = keys[i0:i1], docs[i0:i1]
        total = int(keys.size)
        off = int(header.get("offset", 0))
        limit = header.get("limit")
        if limit is not None:
            keys, docs = keys[off : off + int(limit)], docs[off : off + int(limit)]
        elif off:
            keys, docs = keys[off:], docs[off:]
        return {"total": total}, [keys, docs]

    def _h_scrub(self, header, arrays):
        """On-demand end-to-end corruption scrub: every block CRC + the
        manifest whole-file digests, per space; corrupt segments are
        quarantined server-side instead of ever answering a probe."""
        sp = header.get("space")
        spaces = [sp] if sp else sorted(self.indexes)
        return {
            "shard": self.name,
            "report": {s: self.indexes[s].scrub() for s in spaces},
        }

    def _h_snapshot(self, header, arrays):
        """Consistent-snapshot fence for one space: cut the memtable
        under the shard write lock (no insert can interleave with the
        fence), then name every live file with size + digest."""
        idx = self._space(header)
        with self._lock:
            return {"shard": self.name, "snapshot": idx.snapshot_meta()}

    def _h_fetch_file(self, header, arrays):
        """Raw paged bytes of one snapshot-named file (segments are
        immutable, so pages of one file always compose consistently)."""
        idx = self._space(header)
        data = idx.read_file(
            header["name"],
            int(header.get("offset", 0)),
            header.get("limit"),
        )
        return {"bytes": len(data)}, [np.frombuffer(data, np.uint8)]

    def _h_retire_range(self, header, arrays):
        """Mark ring range ``[lo, hi)`` handed off (idempotent, one
        atomic manifest write) — the cutover's last step per range."""
        idx = self._space(header)
        idx.retire_range(int(header["lo"]), int(header["hi"]))
        return {"handed_off": len(idx.handed_off_ranges())}

    def _h_unretire_range(self, header, arrays):
        """Re-acquire a previously handed-off range (the N→M→N round
        trip) — idempotent."""
        idx = self._space(header)
        idx.unretire_range(int(header["lo"]), int(header["hi"]))
        return {"handed_off": len(idx.handed_off_ranges())}

    def _h_wipe(self, header, arrays):
        """Drop every posting of ONE wipe-allowed space (crash-safe
        committed wipe, doc-id high-water preserved).  Refused for any
        space whose :func:`namespace_policy` does not declare
        ``wipe_allowed``: canary expiry and tenant offboarding are
        namespace-plane verbs, not a general data-deletion API."""
        sp = header.get("space", "")
        if not namespace_policy(sp).wipe_allowed:
            raise ValueError(
                f"wipe is restricted to wipe-allowed namespace prefixes "
                f"(policy {namespace_policy(sp).quota_class!r}), not {sp!r}"
            )
        idx = self.indexes.get(sp)
        if idx is None:
            return {"dropped": 0}  # never provisioned here: idempotent
        with self._lock:
            return {"dropped": int(idx.wipe())}

    def _h_reshard_mark(self, header, arrays):
        """Set/clear/read the mid-reshard fence on every space this node
        hosts (a reshard moves the whole node's ring slice, not one
        space's)."""
        op = header.get("op", "get")
        if op == "set":
            for idx in self.indexes.values():
                idx.set_reshard_mark(str(header["token"]))
        elif op == "clear":
            for idx in self.indexes.values():
                idx.clear_reshard_mark()
        elif op != "get":
            raise ValueError(f"reshard_mark op must be set/clear/get, not {op!r}")
        return {
            "marks": {
                sp: idx.reshard_mark() for sp, idx in self.indexes.items()
            }
        }


def paged_fetch_range(
    call, lo: int, hi: int, *, page: int = 1 << 18, mixed: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """The ONE ``fetch_range`` pagination loop (offset/total/empty-page
    termination), shared by :class:`RemoteIndex` and the fleet client's
    repair plane so the paging contract cannot drift between them.
    ``call(header)`` issues one RPC and returns ``(header, [keys, docs])``.
    """
    parts_k, parts_d = [], []
    off = 0
    while True:
        header = {"lo": int(lo), "hi": int(hi), "offset": off, "limit": int(page)}
        if mixed:
            header["mixed"] = True
        h, (keys, docs) = call(header)
        parts_k.append(np.asarray(keys, np.uint64))
        parts_d.append(np.asarray(docs, np.uint64))
        off += int(parts_k[-1].size)
        if off >= int(h.get("total", off)) or parts_k[-1].size == 0:
            break
    return np.concatenate(parts_k), np.concatenate(parts_d)


class RemoteIndex:
    """Client handle for ONE key space on ONE shard node.

    The per-node building block of ``index/fleet.py`` — and a drop-in
    single-shard remote for code written against ``PersistentIndex``
    (``probe_batch`` / ``insert_batch`` / ``check_and_add_batch`` /
    ``allocate_doc_ids`` / ``log_names``).  Retries ride the RPC layer's
    request-id discipline; ``check_and_add_batch`` retries are safe for
    the same reason (response replay within a server lifetime, and the
    orchestrating fleet client never uses it across one).
    """

    def __init__(
        self,
        address: tuple[str, int],
        *,
        space: str = "bands",
        client: rpc.RpcClient | None = None,
        timeout: float = 10.0,
        retries: int = 3,
        connect=None,
        seed: int = 0,
    ):
        self.address = tuple(address)
        self.space = space
        self.client = client or rpc.RpcClient(
            self.address,
            timeout=timeout,
            retries=retries,
            connect=connect,
            seed=seed,
        )

    def _call(self, method, header=None, arrays=(), **kw):
        h = {"space": self.space}
        h.update(header or {})
        return self.client.call(method, h, arrays, **kw)

    def ping(self, *, timeout: float | None = None) -> bool:
        return self.client.ping(timeout=timeout)

    def probe_batch(self, keys) -> np.ndarray:
        _h, (docs,) = self._call("probe", arrays=[np.asarray(keys, np.uint64)])
        return docs

    def insert_batch(self, keys, docs, *, request_id=None) -> int:
        h, _ = self._call(
            "insert",
            arrays=[np.asarray(keys, np.uint64), np.asarray(docs, np.uint64)],
            request_id=request_id,
        )
        return int(h.get("applied", 0))

    def check_and_add_batch(self, keys, doc_ids) -> np.ndarray:
        _h, (attr,) = self._call(
            "check_and_add",
            arrays=[np.asarray(keys, np.uint64), np.asarray(doc_ids, np.uint64)],
        )
        return attr

    def allocate_doc_ids(self, n: int, *, floor: int = 0) -> np.ndarray:
        _h, (ids,) = self._call("allocate", {"n": int(n), "floor": int(floor)})
        return ids

    def log_names(self, doc_ids, names) -> None:
        self._call(
            "log_names",
            {"names": [str(n) for n in names]},
            arrays=[np.asarray(doc_ids, np.uint64)],
        )

    def doc_id_floor(self) -> int:
        h, _ = self._call("floor")
        return int(h["floor"])

    def stats(self) -> dict:
        h, _ = self._call("stats")
        return h

    def dump_postings(
        self, *, page: int = 1 << 18
    ) -> tuple[np.ndarray, np.ndarray]:
        parts_k, parts_d = [], []
        off = 0
        while True:
            h, (keys, docs) = self._call(
                "dump", {"offset": off, "limit": int(page)}
            )
            parts_k.append(np.asarray(keys, np.uint64))
            parts_d.append(np.asarray(docs, np.uint64))
            off += int(parts_k[-1].size)
            if off >= int(h.get("total", off)) or parts_k[-1].size == 0:
                break
        return np.concatenate(parts_k), np.concatenate(parts_d)

    def checkpoint(self) -> None:
        self._call("checkpoint")

    def wipe(self) -> int:
        """Expire this space's postings (canary spaces only — the server
        refuses others); returns the dropped posting count."""
        h, _ = self._call("wipe")
        return int(h.get("dropped", 0))

    # -- self-healing plane ------------------------------------------------

    def digest(
        self,
        *,
        bits: int | None = None,
        lo: int | None = None,
        hi: int | None = None,
        mixed: bool = False,
    ):
        header: dict = {} if bits is None else {"bits": int(bits)}
        if mixed:
            header["mixed"] = True
            if lo is not None:
                header["lo"], header["hi"] = int(lo), int(hi)
        h, (dig, cnt) = self._call("digest", header)
        return dig, cnt

    def fetch_range(
        self, lo: int, hi: int, *, page: int = 1 << 18, mixed: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        return paged_fetch_range(
            lambda header: self._call("fetch_range", header),
            lo, hi, page=page, mixed=mixed,
        )

    # -- reshard control plane ----------------------------------------------

    def retire_range(self, lo: int, hi: int) -> None:
        self._call("retire_range", {"lo": int(lo), "hi": int(hi)})

    def unretire_range(self, lo: int, hi: int) -> None:
        self._call("unretire_range", {"lo": int(lo), "hi": int(hi)})

    def set_reshard_mark(self, token: str) -> None:
        self._call("reshard_mark", {"op": "set", "token": str(token)})

    def clear_reshard_mark(self) -> None:
        self._call("reshard_mark", {"op": "clear"})

    def reshard_marks(self) -> dict:
        h, _ = self._call("reshard_mark", {"op": "get"})
        return h["marks"]

    def scrub(self) -> dict:
        h, _ = self._call("scrub")
        return h["report"]

    def snapshot_meta(self) -> dict:
        h, _ = self._call("snapshot")
        return h["snapshot"]

    def fetch_file_into(self, name: str, fh, *, page: int = 4 << 20) -> int:
        """Stream one snapshot-named file into ``fh`` paged under the
        frame cap (segments are immutable, so pages compose); returns
        the byte count.  Memory stays bounded at one page — a multi-GB
        compacted segment never materialises client-side."""
        off = 0
        while True:
            h, (chunk,) = self._call(
                "fetch_file", {"name": name, "offset": off, "limit": int(page)}
            )
            chunk = np.asarray(chunk, np.uint8).tobytes()
            if not chunk:
                break
            fh.write(chunk)
            off += len(chunk)
            if len(chunk) < page:
                break
        return off

    def fetch_file(self, name: str, *, page: int = 4 << 20) -> bytes:
        """:meth:`fetch_file_into` for small files whose bytes the
        caller wants in hand."""
        import io

        buf = io.BytesIO()
        self.fetch_file_into(name, buf, page=page)
        return buf.getvalue()

    def close(self) -> None:
        self.client.close()


def serve_main(argv=None) -> int:
    """Standalone shard entry (``python -m advanced_scrapper_tpu.index.remote``).

    Writes the bound port to ``--port-file`` ATOMICALLY after listen, so a
    parent that forked N shards can wait for the files instead of racing
    the bind.  SIGTERM closes cleanly; SIGKILL is the crashsweep's job.
    """
    import argparse
    import signal
    import time as _time

    ap = argparse.ArgumentParser(description=serve_main.__doc__)
    ap.add_argument("--dir", required=True, help="shard index directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default=None)
    ap.add_argument("--spaces", default=",".join(DEFAULT_SPACES))
    ap.add_argument("--cut-postings", type=int, default=1 << 16)
    ap.add_argument("--compact-segments", type=int, default=8)
    ap.add_argument("--name", default="")
    ap.add_argument(
        "--max-inflight-inserts", type=int, default=32,
        help="admission bound on concurrently executing write handlers "
        "(insert/check_and_add); beyond it requests get a counted "
        "RpcOverloaded reject with a retry-after hint (0 = unbounded)",
    )
    ap.add_argument(
        "--insert-rate", type=float, default=0.0,
        help="token-bucket cap on admitted writes/s (0 = unlimited)",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve GET /metrics + /status beside the RPC socket "
        "(0 = ephemeral; omit = only under ASTPU_TELEMETRY)",
    )
    ap.add_argument(
        "--metrics-port-file", default=None,
        help="write the bound metrics port here (atomic, after listen) — "
        "how a parent wires the fleet collector to forked shards",
    )
    args = ap.parse_args(argv)

    if args.metrics_port_file is not None and args.metrics_port is None:
        # asking where the metrics port landed IS asking for the sidecar:
        # a parent waiting on the file must never hang because
        # --metrics-port was omitted and telemetry happened to be off
        args.metrics_port = 0

    srv = IndexShardServer(
        args.dir,
        host=args.host,
        port=args.port,
        spaces=tuple(s for s in args.spaces.split(",") if s),
        cut_postings=args.cut_postings,
        compact_segments=args.compact_segments,
        compact_inline=True,  # forked shards: deterministic compaction,
        name=args.name,       # a chaos/SIGKILL target like everything else
        status_port=args.metrics_port,
        max_inflight_inserts=args.max_inflight_inserts,
        insert_rate=args.insert_rate,
    ).start()
    if args.port_file:
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(args.port_file, str(srv.port).encode())
    if args.metrics_port_file and srv.status_server is not None:
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            args.metrics_port_file, str(srv.status_server.port).encode()
        )

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())
    try:
        while not stop.is_set():
            _time.sleep(0.1)
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())
